"""Slab-arena primitives for the shared-memory object plane.

The reference's plasma store (ray: src/ray/object_manager/plasma/store.h)
is a pre-mapped shm *arena*: clients create/seal objects inside shared
segments and readers map nothing per object. This module is that layout
for ray_tpu: a node's store directory holds

  <store_dir>/index.shm           shared-memory object index (hash table)
  <store_dir>/slabs/seg_<id>.slab pre-sized slab segments (sparse tmpfs)
  <store_dir>/<oid>.obj           legacy one-file objects (spill restores,
                                  cross-node interop, fallback writes)

Writers lease a slab from the raylet (one RPC amortized over many puts),
bump-allocate entries into their private rw mapping, and SEAL each entry
by writing its 8-byte state word last — an atomic header flip, so a
reader can never observe a half-written object as sealed and a writer
killed mid-put leaves a torn (state==0) tail that a rescan discards.
Readers resolve oid -> (segment, offset) through the shared index, map
the segment once per process, and return memoryviews straight into the
arena: no per-object open/flock/stat/mmap.

Entry layout (64-byte aligned, 80-byte header):

  [0:8)    state      b"RTPUSLB1" sealed | b"RTPUSLBX" dead | else torn
  [8:36)   object id  (28 bytes)
  [36:44)  meta_len   u64 LE
  [44:52)  data_len   u64 LE
  [52:60)  entry_total u64 LE (aligned size of header+meta+data)
  [60:64)  crc32 of [8:60)  (torn-header detection beyond the state word)
  [64:80)  reserved
  [80:...) metadata, then data

Index layout (64-byte header, 64-byte open-addressed slots):

  header:  [0:8) b"RTPUIDX1"  [8:16) slot_count u64
  slot:    [0:28) oid  [28:32) state u32 (0 empty, 1 sealed, 2 dead)
           [32:40) seg_id u64  [40:48) offset u64  [48:64) reserved

The index is a HINT, not ground truth: inserts from concurrent writer
processes may (rarely) collide on a slot and lose one entry, and slot
writes are not atomic. Readers therefore always validate the in-slab
entry header (state + oid + crc) before trusting a hit; a miss falls
back to the raylet's ledger over RPC. Torn index slots are harmless by
construction.

Safety rules (the documented live-view hazards):
- slab bytes are NEVER rewritten: allocation only bumps forward, delete
  flips the state word (data region untouched), reclamation unlinks the
  whole segment file — existing mappings keep their pages until the last
  view dies, so a live zero-copy view can never see recycled bytes.
- segments are sparse (ftruncate-sized): an 8MB slab with 1MB written
  costs ~1MB of tmpfs, so generous leases are cheap.
"""

from __future__ import annotations

import logging
import mmap
import os
import struct
import threading
import weakref
import zlib
from collections import OrderedDict
from typing import Dict, Iterable, Optional, Tuple

logger = logging.getLogger(__name__)

OID_SIZE = 28
ALIGN = 64
HDR = 80
STATE_SEALED = b"RTPUSLB1"
STATE_DEAD = b"RTPUSLBX"

# oid namespace for serving-engine KV pages (serve/llm/kv_cache.py):
# entries in this namespace are CACHE, not data — the store's dead-
# writer reclaim sends them to dead ranges instead of adopting them,
# because no process can ever reference a dead replica's pages again
KV_PAGE_OID_PREFIX = b"KVPG"

IDX_MAGIC = b"RTPUIDX1"
IDX_HDR = 64
IDX_SLOT = 64
IDX_PROBE_LIMIT = 128
SLOT_EMPTY, SLOT_SEALED, SLOT_DEAD = 0, 1, 2

INDEX_FILE = "index.shm"
SLAB_DIR = "slabs"


def align_up(n: int) -> int:
    return (n + ALIGN - 1) & ~(ALIGN - 1)


def entry_size(meta_len: int, data_len: int) -> int:
    return align_up(HDR + meta_len + data_len)


def index_path(store_dir: str) -> str:
    return os.path.join(store_dir, INDEX_FILE)


def segment_path(store_dir: str, seg_id: int) -> str:
    return os.path.join(store_dir, SLAB_DIR, f"seg_{seg_id:08d}.slab")


def segment_id_of(path: str) -> Optional[int]:
    name = os.path.basename(path)
    if not (name.startswith("seg_") and name.endswith(".slab")):
        return None
    try:
        return int(name[4:-5])
    except ValueError:
        return None


def create_segment(store_dir: str, seg_id: int, size: int) -> str:
    """Create a sparse, pre-sized slab segment (owner side)."""
    path = segment_path(store_dir, seg_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
    try:
        os.ftruncate(fd, size)
    finally:
        os.close(fd)
    return path


# ----------------------------------------------------------------------
# entry read/write
# ----------------------------------------------------------------------

def _pack_header(oid: bytes, meta_len: int, data_len: int) -> bytes:
    body = oid + struct.pack("<QQQ", meta_len, data_len,
                             entry_size(meta_len, data_len))
    return body + struct.pack("<I", zlib.crc32(body)) + b"\0" * (HDR - 64)


# payload buffers at least this big are written with pwrite instead of a
# memoryview copy into the mapping: a file write fills tmpfs page cache
# in the kernel (no per-page minor fault, no pre-zero of fresh pages),
# measurably faster for bulk objects; mmap and pwrite hit the same pages
# on tmpfs, so readers see one coherent image either way
PWRITE_MIN = 256 * 1024


def pwrite_all(fd: int, buf, pos: int):
    """pwrite to completion: a single pwrite caps at ~2GiB on Linux and
    partial writes are legal — the one authoritative loop for every
    slab write path (bulk put payloads, receive-side chunk landings)."""
    if not isinstance(buf, memoryview):
        buf = memoryview(buf)
    if buf.ndim != 1 or buf.format != "B":
        buf = buf.cast("B")
    n = buf.nbytes
    written = 0
    while written < n:
        written += os.pwrite(fd, buf[written:], pos + written)


def write_entry(mv: memoryview, off: int, oid: bytes, metadata: bytes,
                buffers: Iterable, fd: Optional[int] = None) -> int:
    """Write one entry into a writable segment view and SEAL it (state
    word written last). Returns the aligned entry size."""
    meta_len = len(metadata)
    pos = off + HDR
    if meta_len:
        mv[pos : pos + meta_len] = metadata
        pos += meta_len
    data_len = 0
    for buf in buffers:
        if not isinstance(buf, (bytes, bytearray, memoryview)):
            buf = memoryview(buf)
        if isinstance(buf, memoryview) and (buf.ndim != 1 or buf.format != "B"):
            buf = buf.cast("B")
        n = buf.nbytes if isinstance(buf, memoryview) else len(buf)
        if fd is not None and n >= PWRITE_MIN:
            # without the completion loop the entry seals with data_len
            # covering a zero-filled tail
            pwrite_all(fd, buf, pos)
        else:
            mv[pos : pos + n] = buf
        pos += n
        data_len += n
    total = entry_size(meta_len, data_len)
    # real header now that data_len is known; state word LAST = the seal
    hdr = _pack_header(oid, meta_len, data_len)
    mv[off + 8 : off + HDR] = hdr[: HDR - 8]
    mv[off : off + 8] = STATE_SEALED
    return total


def _parse_header(raw: bytes) -> Optional[Tuple[bytes, int, int, int]]:
    """(oid, meta_len, data_len, entry_total) from header bytes [8:64),
    or None if the crc doesn't hold (torn header)."""
    body, crc = raw[:52], struct.unpack_from("<I", raw, 52)[0]
    if zlib.crc32(body) != crc:
        return None
    oid = body[:OID_SIZE]
    meta_len, data_len, total = struct.unpack_from("<QQQ", body, OID_SIZE)
    if total != entry_size(meta_len, data_len):
        return None
    return oid, meta_len, data_len, total


def read_entry_at(mm, off: int, size: int, oid: Optional[bytes] = None,
                  ) -> Optional[Tuple[bytes, memoryview, int]]:
    """Validate + read a sealed entry: (metadata, data_view, entry_total).
    None if the entry is not sealed, torn, out of bounds, or (when given)
    belongs to a different oid."""
    if off < 0 or off + HDR > size:
        return None
    if bytes(mm[off : off + 8]) != STATE_SEALED:
        return None
    parsed = _parse_header(bytes(mm[off + 8 : off + 64]))
    if parsed is None:
        return None
    eoid, meta_len, data_len, total = parsed
    if oid is not None and eoid != oid:
        return None
    if off + total > size:
        return None
    metadata = bytes(mm[off + HDR : off + HDR + meta_len])
    data = memoryview(mm)[off + HDR + meta_len : off + HDR + meta_len + data_len]
    return metadata, data, total


def entry_state_at(mm, off: int, size: int, oid: Optional[bytes] = None) -> Optional[bytes]:
    """STATE_SEALED / STATE_DEAD for a valid entry (of ``oid`` when given),
    None for anything torn/out-of-bounds."""
    if off < 0 or off + HDR > size:
        return None
    state = bytes(mm[off : off + 8])
    if state not in (STATE_SEALED, STATE_DEAD):
        return None
    parsed = _parse_header(bytes(mm[off + 8 : off + 64]))
    if parsed is None:
        return None
    if oid is not None and parsed[0] != oid:
        return None
    return state


def scan_segment(path: str):
    """Yield (oid, off, meta_len, data_len, entry_total, dead) for every
    valid entry of a segment, stopping at the first torn/free entry —
    allocation is strictly bump-forward, so nothing valid can follow a
    torn entry (a writer killed mid-put leaves exactly one torn tail)."""
    try:
        size = os.path.getsize(path)
        if size < HDR:
            return
        with open(path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    except (OSError, ValueError):
        return
    try:
        off = 0
        while off + HDR <= size:
            state = bytes(mm[off : off + 8])
            if state not in (STATE_SEALED, STATE_DEAD):
                return
            parsed = _parse_header(bytes(mm[off + 8 : off + 64]))
            if parsed is None:
                return
            oid, meta_len, data_len, total = parsed
            if off + total > size:
                return
            yield oid, off, meta_len, data_len, total, state == STATE_DEAD
            off += total
    finally:
        try:
            mm.close()
        except BufferError:
            pass


# ----------------------------------------------------------------------
# hole-punch reclamation (fallocate PUNCH_HOLE|KEEP_SIZE)
# ----------------------------------------------------------------------

PAGE = mmap.PAGESIZE
FALLOC_FL_KEEP_SIZE = 0x01
FALLOC_FL_PUNCH_HOLE = 0x02

_libc = None
_punch_broken = False  # sticky: first EOPNOTSUPP/ENOSYS disables the pass


def punch_span(off: int, length: int, page: int = PAGE
               ) -> Optional[Tuple[int, int]]:
    """The page-aligned interior of a dead range ``[off, off+length)``
    that can be hole-punched while PRESERVING the entry header at
    ``off`` — scans must still traverse the range via its (tombstone)
    header, so the first HDR bytes never go inside the hole. Returns
    ``(start, nbytes)`` or None when no whole page fits."""
    start = (off + HDR + page - 1) // page * page
    end = (off + length) // page * page
    if end <= start:
        return None
    return start, end - start


def punch_range(fd: int, start: int, nbytes: int) -> bool:
    """fallocate(PUNCH_HOLE | KEEP_SIZE) one range: the file size and
    every existing mapping stay intact (readers keep valid views — the
    punched pages read back as zeros), the backing tmpfs pages are
    freed. Returns False (sticky, process-wide) where unsupported."""
    global _libc, _punch_broken
    if _punch_broken or nbytes <= 0:
        return False
    try:
        import ctypes

        if _libc is None:
            lib = ctypes.CDLL(None, use_errno=True)
            lib.fallocate.argtypes = [ctypes.c_int, ctypes.c_int,
                                      ctypes.c_longlong, ctypes.c_longlong]
            lib.fallocate.restype = ctypes.c_int
            _libc = lib
        if _libc.fallocate(
            fd, FALLOC_FL_PUNCH_HOLE | FALLOC_FL_KEEP_SIZE, start, nbytes
        ) != 0:
            import errno

            if ctypes.get_errno() in (errno.EOPNOTSUPP, errno.ENOSYS):
                _punch_broken = True
            return False
        return True
    except (OSError, AttributeError, TypeError):
        _punch_broken = True
        return False


def write_dead_tombstone(fd: int, off: int, total: int) -> bool:
    """Overwrite the entry header at ``off`` with a DEAD header whose
    entry_total covers the whole ``total``-byte (coalesced, entry-
    aligned) range, so a scan hops the punched range in ONE step —
    interior entries' headers are about to be zeroed by the punch, and
    without the covering tombstone the scan would stop at the first
    zeroed state word and strand every sealed entry behind it."""
    if total < align_up(HDR):
        return False
    hdr = _pack_header(b"\0" * OID_SIZE, 0, total - HDR)
    try:
        os.pwrite(fd, hdr[: HDR - 8], off + 8)
        os.pwrite(fd, STATE_DEAD, off)
        return True
    except OSError:
        return False


def mark_dead_at(store_dir: str, seg_id: int, off: int) -> bool:
    """Flip one entry's state word to DEAD via pwrite. The data region is
    untouched, so live zero-copy views of the entry stay intact; new
    resolves see DEAD and miss."""
    try:
        fd = os.open(segment_path(store_dir, seg_id), os.O_WRONLY)
    except OSError:
        return False
    try:
        os.pwrite(fd, STATE_DEAD, off)
        return True
    except OSError:
        return False
    finally:
        os.close(fd)


def wipe_entry_states(path: str):
    """Zero every entry's state word so a recycled segment scans as
    empty (a stale sealed header at exactly the new writer's bump offset
    would otherwise resurrect a dead object on rescan). Only called on
    all-dead segments that no process can map (exclusive-flock proof)."""
    offs = [e[1] for e in scan_segment(path)]
    if not offs:
        return
    fd = os.open(path, os.O_WRONLY)
    try:
        for off in offs:
            os.pwrite(fd, b"\0" * 8, off)
    finally:
        os.close(fd)


# ----------------------------------------------------------------------
# shared-memory index
# ----------------------------------------------------------------------

class SharedIndex:
    """Open-addressed oid -> (seg, off) table in a shared mmap.

    Concurrency model: writers insert without locks (one slot claim can
    rarely be lost to a racing writer); readers validate every hit
    against the in-slab header, so a torn or stale slot degrades to a
    miss, never a wrong object."""

    def __init__(self, path: str, slots: int = 1 << 16, create: bool = False):
        self.path = path
        existing = os.path.exists(path)
        if not existing and not create:
            raise FileNotFoundError(path)
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if os.fstat(fd).st_size < IDX_HDR + IDX_SLOT:
                os.ftruncate(fd, IDX_HDR + slots * IDX_SLOT)
                os.pwrite(fd, IDX_MAGIC + struct.pack("<Q", slots), 0)
            self._mm = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        if bytes(self._mm[:8]) != IDX_MAGIC:
            raise IOError(f"corrupt arena index {path}")
        self.slots = struct.unpack_from("<Q", self._mm, 8)[0]
        if IDX_HDR + self.slots * IDX_SLOT > len(self._mm):
            raise IOError(f"truncated arena index {path}")

    def close(self):
        try:
            self._mm.close()
        except BufferError:
            pass

    def _slot_off(self, i: int) -> int:
        return IDX_HDR + (i % self.slots) * IDX_SLOT

    def _probe(self, oid: bytes):
        # hash ALL the id bytes: sibling objects (one task's returns, a
        # driver's puts) share a 24-byte task-id prefix, so a prefix-only
        # probe start would pile every sibling into one 128-slot window
        # and strand the 129th
        start = zlib.crc32(oid)
        for k in range(min(IDX_PROBE_LIMIT, self.slots)):
            yield self._slot_off(start + k)

    def lookup(self, oid: bytes) -> Optional[Tuple[int, int]]:
        mm = self._mm
        for so in self._probe(oid):
            raw = bytes(mm[so : so + 48])
            state = struct.unpack_from("<I", raw, OID_SIZE)[0]
            if state == SLOT_EMPTY:
                return None
            if state == SLOT_SEALED and raw[:OID_SIZE] == oid:
                seg, off = struct.unpack_from("<QQ", raw, 32)
                return seg, off
        return None

    def insert(self, oid: bytes, seg_id: int, off: int) -> bool:
        mm = self._mm
        tomb = None
        target = None
        for so in self._probe(oid):
            raw = bytes(mm[so : so + 32])
            state = struct.unpack_from("<I", raw, OID_SIZE)[0]
            if raw[:OID_SIZE] == oid and state != SLOT_EMPTY:
                target = so  # re-put / restore of a known oid: update in place
                break
            if state == SLOT_EMPTY:
                target = so
                break
            if state == SLOT_DEAD and tomb is None:
                tomb = so
        if target is None:
            target = tomb
        if target is None:
            return False  # probe window full: reader falls back to RPC
        # fields first, state last (readers validate against the slab
        # anyway, so a torn claim is a miss, not a lie)
        mm[target : target + OID_SIZE] = oid
        struct.pack_into("<QQ", mm, target + 32, seg_id, off)
        struct.pack_into("<I", mm, target + OID_SIZE, SLOT_SEALED)
        return True

    def mark_dead(self, oid: bytes):
        mm = self._mm
        for so in self._probe(oid):
            raw = bytes(mm[so : so + 32])
            state = struct.unpack_from("<I", raw, OID_SIZE)[0]
            if state == SLOT_EMPTY:
                return
            if state == SLOT_SEALED and raw[:OID_SIZE] == oid:
                struct.pack_into("<I", mm, so + OID_SIZE, SLOT_DEAD)
                return


# ----------------------------------------------------------------------
# per-process arena view (reader cache)
# ----------------------------------------------------------------------

class _ArenaView:
    """One process's lens onto a store's arena: the shared index plus a
    bounded cache of read-only segment mappings ('readers pin segments':
    a cached mapping keeps the pages alive even after the owner unlinks
    the segment file)."""

    # a cached mapping (and its reader flock) unused this long is closed
    # on the next cache access: a long-lived driver that once read from
    # a segment must not pin it against hole-punch reclamation forever —
    # live exported views still protect their mapping (BufferError)
    IDLE_CLOSE_S = 15.0

    def __init__(self, store_dir: str, cache_segments: int = 64):
        self.store_dir = store_dir
        self.lock = threading.Lock()
        self.index: Optional[SharedIndex] = None
        # seg_id -> (mm, size, file, [last_used_monotonic])
        self.segs: "OrderedDict[int, tuple]" = OrderedDict()
        self.cache_segments = cache_segments
        self._index_miss_until = 0.0
        self._idle_sweep_at = 0.0

    def _index(self) -> Optional[SharedIndex]:
        if self.index is not None:
            return self.index
        # negative-cache the missing index (legacy stores never grow
        # one): without this, every read in a non-arena store pays a
        # stat + exception on the hot path. Arena stores create the
        # index before any client learns the store_dir, so the TTL only
        # ever delays legacy dirs.
        import time as _time

        now = _time.monotonic()
        if now < self._index_miss_until:
            return None
        try:
            self.index = SharedIndex(index_path(self.store_dir))
        except (OSError, IOError):
            self._index_miss_until = now + 1.0
            return None
        return self.index

    def segment(self, seg_id: int) -> Optional[Tuple[mmap.mmap, int]]:
        import time as _time

        now = _time.monotonic()
        with self.lock:
            if now - self._idle_sweep_at > self.IDLE_CLOSE_S / 3:
                self._idle_sweep_at = now
                self._sweep_idle_locked(now)
            ent = self.segs.get(seg_id)
            if ent is not None:
                ent[3][0] = now
                self.segs.move_to_end(seg_id)
                return ent[0], ent[1]
        path = segment_path(self.store_dir, seg_id)
        try:
            f = open(path, "rb")
        except OSError:
            return None
        try:
            # segment-granularity SHARED flock ("readers pin segments"):
            # held for the cache entry's lifetime, it lets the owner's
            # recycling pool prove no process can see a segment before
            # rewriting it (EXCLUSIVE non-blocking test) — per-object
            # reads stay flock-free
            import fcntl

            fcntl.flock(f.fileno(), fcntl.LOCK_SH)
            size = os.fstat(f.fileno()).st_size
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError):
            f.close()
            return None
        # the flock fd must outlive every exported view of the mapping
        # (a recycled-while-viewed segment would be a torn read)
        weakref.finalize(mm, f.close)
        import time as _time

        ent = (mm, size, f, [_time.monotonic()])
        with self.lock:
            won = self.segs.setdefault(seg_id, ent)
            if won is not ent:
                self._close_entry(ent)
                return won[0], won[1]
            self._sweep_locked()
            while len(self.segs) > self.cache_segments:
                _, old = self.segs.popitem(last=False)
                self._close_entry(old)
        return mm, size

    @staticmethod
    def _close_entry(ent):
        mm, _sz, f = ent[:3]
        try:
            mm.close()
        except BufferError:
            return  # views alive: the finalize closes f when they die
        f.close()

    def _sweep_idle_locked(self, now: float):
        """Close cached mappings unused for IDLE_CLOSE_S whose views are
        all gone — the reader flock goes with the mapping, releasing the
        segment for the owner's hole-punch / recycle passes. Read paths
        retry once on a concurrently-swept mapping (ValueError), so a
        sweep can never turn a live object into a miss."""
        for sid in list(self.segs.keys()):
            ent = self.segs[sid]
            if now - ent[3][0] < self.IDLE_CLOSE_S:
                continue
            try:
                ent[0].close()
            except BufferError:
                continue  # exported views keep it (and its flock) alive
            ent[2].close()
            del self.segs[sid]

    def _sweep_locked(self):
        """Drop cached mappings of segments the owner has unlinked or
        pooled — without this, the reader cache would pin every
        reclaimed segment's pages (and its recycle-blocking flock) until
        LRU churn got around to it. A mapping with live exported views
        refuses to close (BufferError) and is kept: the pages stay valid
        exactly as long as someone can still see them."""
        for sid in list(self.segs.keys()):
            if os.path.exists(segment_path(self.store_dir, sid)):
                continue
            ent = self.segs[sid]
            try:
                ent[0].close()
            except BufferError:
                continue
            ent[2].close()
            del self.segs[sid]

    def sweep(self):
        with self.lock:
            self._sweep_locked()

    def drop_segment(self, seg_id: int) -> bool:
        """Release OUR cached mapping of one segment (and its SHARED
        flock) so the owner's hole-punch pass can prove no process views
        it. Returns False when exported zero-copy views keep the mapping
        alive — the punch pass then skips the segment."""
        with self.lock:
            ent = self.segs.get(seg_id)
            if ent is None:
                return True
            try:
                ent[0].close()
            except BufferError:
                return False  # live exported views: segment stays pinned
            ent[2].close()
            del self.segs[seg_id]
            return True

    def resolve(self, oid: bytes) -> Optional[Tuple[int, int, mmap.mmap, int]]:
        idx = self._index()
        if idx is None:
            return None
        hit = idx.lookup(oid)
        if hit is None:
            return None
        seg_id, off = hit
        ent = self.segment(seg_id)
        if ent is None:
            return None
        mm, size = ent
        return seg_id, off, mm, size


_views: Dict[str, _ArenaView] = {}
_views_lock = threading.Lock()


def view(store_dir: str) -> _ArenaView:
    v = _views.get(store_dir)
    if v is None:
        with _views_lock:
            v = _views.setdefault(store_dir, _ArenaView(store_dir))
    return v


def drop_view(store_dir: str):
    """Release one store's per-process arena state (disconnect/shutdown):
    cached segment mappings, their flock fds, and the index mapping —
    otherwise a long-lived process cycling init()/shutdown() pins every
    dead session's tmpfs pages until exit. Mappings with live exported
    views survive (BufferError) and close when the views die."""
    with _views_lock:
        v = _views.pop(store_dir, None)
    if v is None:
        return
    with v.lock:
        for ent in v.segs.values():
            v._close_entry(ent)
        v.segs.clear()
        if v.index is not None:
            v.index.close()
            v.index = None


def read(store_dir: str, oid: bytes
         ) -> Optional[Tuple[bytes, memoryview, int]]:
    """(metadata, zero-copy data view, seg_id) via the shared index, or
    None. Flock-free: validation is the in-slab sealed header."""
    for _ in range(2):
        r = view(store_dir).resolve(oid)
        if r is None:
            return None
        seg_id, off, mm, size = r
        try:
            got = read_entry_at(mm, off, size, oid=oid)
        except ValueError:
            # cache race: a concurrent sweep (idle-close, LRU) closed
            # this viewless mapping between resolve and the slice —
            # resolve again (it re-opens), never report a live object
            # as a miss off a swept mapping
            continue
        if got is None:
            return None
        metadata, data, _total = got
        return metadata, data, seg_id
    return None


def read_at(store_dir: str, seg_id: int, off: int, oid: bytes
            ) -> Optional[Tuple[bytes, memoryview]]:
    """Ledger-directed read (owner side / RPC-resolved): skip the index."""
    for _ in range(2):
        ent = view(store_dir).segment(seg_id)
        if ent is None:
            return None
        mm, size = ent
        try:
            got = read_entry_at(mm, off, size, oid=oid)
        except ValueError:
            continue  # swept under us: re-open and retry once
        if got is None:
            return None
        return got[0], got[1]
    return None


def exists(store_dir: str, oid: bytes) -> bool:
    for _ in range(2):
        r = view(store_dir).resolve(oid)
        if r is None:
            return False
        seg_id, off, mm, size = r
        try:
            return entry_state_at(mm, off, size, oid=oid) == STATE_SEALED
        except ValueError:
            continue  # swept under us: re-open and retry once
    return False


def state_at(store_dir: str, seg_id: int, off: int, oid: bytes) -> Optional[bytes]:
    for _ in range(2):
        ent = view(store_dir).segment(seg_id)
        if ent is None:
            return None
        mm, size = ent
        try:
            return entry_state_at(mm, off, size, oid=oid)
        except ValueError:
            continue  # swept under us: re-open and retry once
    return None


def discard(store_dir: str, oid: bytes) -> bool:
    """Mark a slab object dead from ANY process (test/chaos surface — the
    arena analog of unlinking an .obj file)."""
    v = view(store_dir)
    r = v.resolve(oid)
    if r is None:
        return False
    seg_id, off, mm, size = r
    try:
        if entry_state_at(mm, off, size, oid=oid) != STATE_SEALED:
            return False
    except ValueError:
        return False  # mapping closed by a concurrent sweep
    if not mark_dead_at(store_dir, seg_id, off):
        return False
    idx = v._index()
    if idx is not None:
        idx.mark_dead(oid)
    return True


# ----------------------------------------------------------------------
# writer side
# ----------------------------------------------------------------------

class SlabWriter:
    """Bump allocator over the current leased slab of one process.

    ``try_put`` is the whole fast path: reserve a range, memcpy the
    buffers, seal with the state-word flip, publish in the shared index.
    It never blocks on the raylet — when the slab is out of room it
    returns None and the caller runs the lease protocol (``attach`` a
    fresh segment granted by the owner, sealing the old one)."""

    def __init__(self, store_dir: str):
        self.store_dir = store_dir
        self.lock = threading.RLock()
        self.seg_id: Optional[int] = None
        self._mm: Optional[mmap.mmap] = None
        self._mv: Optional[memoryview] = None
        self._fd: Optional[int] = None  # bulk payloads go through pwrite
        self._off = 0
        self._size = 0
        self._last_lease = 0

    def attach(self, seg_id: int, size: int):
        """Adopt a freshly leased segment (file already created+sized by
        the owner)."""
        with self.lock:
            self._detach_locked()
            fd = os.open(segment_path(self.store_dir, seg_id), os.O_RDWR)
            try:
                # writers hold the SHARED flock too: the recycling pool's
                # exclusive probe must also see a zombie writer (live
                # process whose raylet connection dropped and whose slab
                # was reclaimed) — without this its rw mapping could
                # bump-write over a re-leased segment
                import fcntl

                fcntl.flock(fd, fcntl.LOCK_SH)
                self._mm = mmap.mmap(fd, size)
            except (OSError, ValueError):
                os.close(fd)
                raise
            self._fd = fd
            self._mv = memoryview(self._mm)
            self.seg_id = seg_id
            self._off = 0
            self._size = size
            self._last_lease = size

    def _detach_locked(self):
        if self._mm is None:
            return
        try:
            self._mv.release()
        except BufferError:
            pass
        try:
            self._mm.close()
        except BufferError:
            pass  # the mapping dies with its last exported view
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
        self._mm = None
        self._mv = None
        self.seg_id = None

    def close(self):
        with self.lock:
            self._detach_locked()

    def take_seal(self) -> Optional[dict]:
        """Detach the current slab and return its seal record (rides the
        next lease RPC so the owner can credit the unused tail)."""
        with self.lock:
            if self.seg_id is None:
                return None
            seal = {"seg_id": self.seg_id, "used": self._off}
            self._detach_locked()
            return seal

    def remaining(self) -> int:
        with self.lock:
            return self._size - self._off if self._mm is not None else 0

    def lease_size_for(self, entry_total: int, slab_default: int,
                       slab_min: int) -> int:
        """Adaptive slab sizing: start small, double per lease up to the
        default, always covering the triggering entry. Segments are
        sparse, so the cost of a generous lease is accounting, not
        memory."""
        nxt = min(slab_default, max(slab_min, self._last_lease * 2))
        return max(entry_total, nxt)

    def try_reserve(self, entry_total: int) -> Optional[Tuple[int, int]]:
        """Bump-allocate one entry range WITHOUT writing it: the caller
        (receive-side slab assembly) pwrites chunk payloads straight into
        the segment file at their offsets and seals with the same
        state-word flip ``write_entry`` uses. Until that seal the entry
        reads as torn — a receiver killed mid-transfer leaves exactly
        the tail a crash rescan already discards. Returns
        ``(seg_id, off)`` or None when the current slab can't fit it."""
        with self.lock:
            if self._mm is None or self._off + entry_total > self._size:
                return None
            off = self._off
            self._off += entry_total
            # recycled pooled segments are only state-wiped at their OLD
            # entry offsets: scrub our new entry's state word so a stale
            # sealed magic can never make the in-progress entry scannable
            self._mv[off : off + 8] = b"\0" * 8
            return self.seg_id, off

    def try_put(self, oid: bytes, metadata: bytes, buffers,
                total_data_len: int) -> Optional[dict]:
        """Write+seal+index one object; returns the accounting report
        entry, or None when the current slab can't fit it."""
        total = entry_size(len(metadata), total_data_len)
        with self.lock:
            if self._mm is None or self._off + total > self._size:
                return None
            off = self._off
            self._off += total
            write_entry(self._mv, off, oid, metadata, buffers, fd=self._fd)
            seg_id = self.seg_id
        idx = view(self.store_dir)._index()
        if idx is not None:
            idx.insert(oid, seg_id, off)
        return {"o": oid, "s": seg_id, "f": off, "n": total}
