"""Core worker: the per-process runtime embedded in drivers and workers.

Analog of the reference's CoreWorker (ray: src/ray/core_worker/core_worker.h:284):
task submission with submitter-side dependency resolution
(ray: transport/dependency_resolver.h — owned in-memory args are awaited and
inlined before the lease request; plasma refs are left for the raylet), an
in-process memory store for small objects (ray: memory_store.h:43), the plasma
provider for shm objects (ray: plasma_store_provider.h:88), owner-side retry
bookkeeping (ray: task_manager.h:173), a simplified reference counter
(ray: reference_count.h:61), and per-caller ordered actor submission
(ray: sequential_actor_submit_queue.h).

Sync user code runs on the main/executor threads; all IO rides a dedicated
asyncio loop thread (rpcio.EventLoopThread), mirroring the reference's
io_context-per-process model.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import (faultsim, memview, object_store,
                              serialization, slab_arena)
from ray_tpu._private.common import SchedulingStrategy, TaskSpec, rewrite_resources_for_pg
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import (ActorID, JobID, ObjectID, TaskID,
                                  TaskIDMinter, WorkerID, object_id_binary)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpcio import (Connection, EventLoopThread, RpcServer,
                                    call_with_retries, connect)

logger = logging.getLogger(__name__)

# Thread-local marker for "currently deserializing the value of container X":
# refs rebuilt inside record X as their borrow provenance so the container's
# owner can hand the borrow off when X is released (reference_count.h
# borrowed-through-object tracking).
_DESER_CTX = threading.local()


class _deser_container:
    def __init__(self, container_oid):
        self.oid = container_oid

    def __enter__(self):
        self.prev = getattr(_DESER_CTX, "container", None)
        _DESER_CTX.container = self.oid

    def __exit__(self, *exc):
        _DESER_CTX.container = self.prev


_tracing_mod = None


def _tracing_ctx():
    """Current span context for propagation into outgoing specs (no-op
    None when tracing is off). The tracing module is cached after the
    first call: the per-call import machinery (sys.modules lookup plus
    the from-list binding) is measurable on the submit hot path."""
    global _tracing_mod
    tracing = _tracing_mod
    if tracing is None:
        try:
            from ray_tpu.util import tracing
        except Exception:
            return None
        _tracing_mod = tracing
    try:
        if tracing.is_enabled():
            return tracing.current_context() or tracing.propagation_context()
        # Not locally enabled, but an adopted remote context still rides
        # through (multi-hop task graphs keep their trace).
        return tracing.propagation_context()
    except Exception:
        return None


# --- control-plane stage timing (BENCH_CONTROL_PLANE) ------------------
# Gated on cfg.control_plane_stage_timing: the bench lane (and anyone
# chasing a microsecond) gets per-stage latency histograms on the submit
# path; the default path pays one attribute check per call. Per-stage
# children are cached in a plain dict — same posture as rpcio._RpcMetrics.
_STAGE_HISTS: Dict[str, Any] = {}


def _stage_record(stage: str, seconds: float):
    h = _STAGE_HISTS.get(stage)
    if h is None:
        from ray_tpu._private import metrics_core as mc

        h = _STAGE_HISTS[stage] = mc.registry().histogram(
            "control_plane_stage_seconds",
            "Per-stage control-plane latency (see BENCH_CONTROL_PLANE)",
            scale=mc.LATENCY,
        ).labels(stage=stage)
    h.record(seconds)


class TaskTemplate:
    """Immutable per-callsite submit template (control-plane fast path):
    everything about a ``.remote()`` call that does not vary call to call
    — resources (PG-rewritten once), scheduling, the serialized function,
    retry policy, runtime env — is computed ONCE here, so the per-call
    path only mints a task id and encodes the arguments. The API layer
    caches one template per RemoteFunction / actor method; ``.options()``
    yields a new options set and therefore a new template, and ``worker``
    pins the CoreWorker the template was built against so a reconnect
    invalidates the cache. The resources/scheduling objects are SHARED
    across every spec stamped from the template and must not be mutated
    driver-side (the raylet unpickles its own copies)."""

    __slots__ = ("worker", "name", "func_blob", "method_name",
                 "num_returns", "resources", "scheduling", "max_retries",
                 "retry_exceptions", "runtime_env", "actor_id",
                 "concurrency_group", "minter")


def _log_span_fields(result: dict) -> dict:
    """Task-event fields from an executor result's exact log byte range
    (see logplane.attach_result_span)."""
    span = result.get("log_span")
    if not span:
        return {}
    return {"log_file": span["file"], "log_start": span["start"],
            "log_end": span["end"]}


class GetTimeoutError(TimeoutError):
    pass


class ActorDiedError(RuntimeError):
    pass


class WorkerDiedError(RuntimeError):
    """The worker process executing the task died (crash, OOM kill, node
    loss) — a SYSTEM failure, typed so callers (e.g. serve's replica-death
    retry) can match on class instead of message text. Analog of
    ray.exceptions.WorkerCrashedError."""
    pass


class TaskCancelledError(RuntimeError):
    pass


class CoreWorker:
    def __init__(
        self,
        raylet_host: str,
        raylet_port: int,
        gcs_host: str,
        gcs_port: int,
        is_driver: bool,
        job_id: Optional[bytes] = None,
        namespace: Optional[str] = None,
    ):
        self.client_id = WorkerID.from_random().hex()
        self._caller_id = self.client_id.encode()  # spec-stamp fast path
        # chaos identity (faultsim partition rules match on it): drivers
        # and workers are labeled so raylet-to-raylet partitions miss them
        faultsim.set_self_id(f"worker:{self.client_id[:12]}")
        self.is_driver = is_driver
        self.namespace = namespace or "default"
        self.executor = None  # set by TaskExecutor on worker processes
        self.io = EventLoopThread(name=f"coreworker-io-{self.client_id[:6]}")
        self.raylet: Connection = self.io.run(
            connect(raylet_host, raylet_port, handler=self, name="raylet-conn")
        )
        # workers spawned during a GCS outage must come up once it returns:
        # give non-drivers the same patience as the raylet reconnect loop
        # (a wall-clock budget — connect() retries with exponential backoff
        # until the deadline). Drivers get a SHORT budget instead: an
        # interactive init() against a dead/mistyped address should fail in
        # seconds, not ride 30 capped-backoff attempts for a minute.
        self.gcs: Connection = self.io.run(
            connect(gcs_host, gcs_port, handler=self, name="gcs-conn",
                    total_timeout=10.0 if is_driver
                    else cfg.gcs_client_reconnect_timeout_s)
        )
        self.gcs_addr = (gcs_host, gcs_port)
        if is_driver and job_id is None:
            job_id = self.io.run(
                self.gcs.request("register_job", {"namespace": self.namespace,
                                                  "driver": {"pid": os.getpid()}})
            )["job_id"]
        self.job_id = job_id or JobID.from_int(0).binary()
        self.io.run(
            self.gcs.request(
                "register_client",
                {"client_id": self.client_id, "job_id": self.job_id,
                 "is_driver": is_driver},
            )
        )
        # Workers serve a direct RPC endpoint so drivers holding a lease
        # push tasks straight here, skipping the raylet per task (ray:
        # core worker gRPC server + direct_task_transport.cc).
        self.direct_server: Optional[RpcServer] = None
        direct_port = None
        if not is_driver:
            self.direct_server = RpcServer(
                self, host=os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1"),
                port=0,
            )
            direct_port = self.io.run(self.direct_server.start())
        reply = self.io.run(
            self.raylet.request(
                "register_client",
                {"client_id": self.client_id,
                 "kind": "driver" if is_driver else "worker",
                 "job_id": self.job_id, "pid": os.getpid(),
                 # echo the raylet's spawn key: containerized workers
                 # report a pid the raylet never saw (the engine client's
                 # pid differs from the in-container worker's), so the
                 # raylet matches its _Worker record by this key first
                 "spawn_id": os.environ.get("RAY_TPU_WORKER_SPAWN_ID"),
                 "direct_port": direct_port},
            )
        )
        self.node_id: str = reply["node_id"]
        self.store_dir: str = reply["store_dir"]
        self.node_resources: Dict[str, float] = reply.get("resources_total", {})
        self.node_labels: Dict[str, str] = reply.get("labels", {})
        self.addr = (self.node_id, self.client_id)
        # slab-arena write path (slab_arena.py): this client leases write
        # slabs from its raylet and bump-allocates puts/results into the
        # mmap'd segment; accounting is self-reported in batches
        self.arena_enabled = bool(reply.get("arena"))
        self._slab_writer = (
            slab_arena.SlabWriter(self.store_dir) if self.arena_enabled
            else None
        )
        self._slab_lease_lock = threading.Lock()
        self._slab_reports: List[dict] = []
        self._slab_flushing = False
        self._slab_refill_task = None
        self._pending_seals: List[dict] = []
        if is_driver:
            self.task_id = TaskID.for_driver(JobID(self.job_id))
        else:
            self.task_id = TaskID.for_task(JobID(self.job_id))
        # owner-side state
        self._lock = threading.Lock()
        self._futures: Dict[bytes, concurrent.futures.Future] = {}
        self._memory_store: Dict[bytes, Tuple[bytes, bytes]] = {}
        self._pinned_buffers: Dict[bytes, object_store.ObjectBuffer] = {}
        self._specs_inflight: Dict[bytes, TaskSpec] = {}
        self._put_index = 0
        self._local_refs: Dict[bytes, int] = {}
        self._owned: set = set()
        # ownership-based object directory (ray:
        # src/ray/object_manager/ownership_based_object_directory.h +
        # reference_count.h:61): the OWNER is the authority on where its
        # objects have copies; raylets query here first and treat the GCS
        # directory as bootstrap/cache, so a GCS restart mid-transfer
        # doesn't stall pulls on a full location replay.
        self._owned_locations: Dict[bytes, set] = {}
        # Lock-free queue of ref releases deferred from ObjectRef.__del__
        # (GC can fire inside locked sections; see defer_ref_release).
        self._deferred_releases: deque = deque()
        # woken by producers; a timed wait stays as the safety net so a
        # set() lost to a race costs 0.5s, not forever (and the idle drain
        # thread no longer wakes 50x/s on every process)
        self._release_event = threading.Event()
        # tick-batched task submission buffer (see _finish_submit)
        self._submit_buf: List[TaskSpec] = []
        self._submit_flushing = False
        # cross-thread submission inbox (see _enqueue_submit)
        self._submit_inbox: deque = deque()
        self._inbox_lock = threading.Lock()
        self._inbox_scheduled = False
        # submission-stage breadcrumbs (task_id -> last stage string):
        # costs one dict write per transition and makes a stranded task
        # diagnosable from the get()-stall dump — which stage ate it.
        self._submit_stage: Dict[bytes, str] = {}
        # Strong refs for fire-and-forget io-loop tasks. asyncio's loop
        # holds only WEAK task references: an unreferenced pending task can
        # be garbage-collected mid-await, silently skipping its finally
        # (observed: a GC'd _direct_pump left its key registered forever,
        # stranding every later task of that scheduling class — the
        # round-4 full-suite hang). Every create_task here must land in
        # this set (or another live structure) until done.
        self._bg_tasks: set = set()
        # direct task push over worker leases (ray:
        # direct_task_transport.cc): per-scheduling-class pending queues,
        # one pump task per active class, cached conns to leased workers
        self._direct_q: Dict[tuple, deque] = {}
        # direct-path placement latency (PR 6's raylet histogram only saw
        # raylet-routed tasks): enqueue-on-the-direct-queue -> pushed to a
        # leased worker, recorded as
        # raylet_task_placement_latency_seconds{path="direct"} in THIS
        # driver's registry (drivers ride the cluster scrape). Specs that
        # fall back to raylet routing drop their stamp — the raylet's
        # path="raylet" series takes over from its own ready queue.
        self._direct_ready_at: Dict[bytes, float] = {}
        self._direct_placement_lat = None
        # key -> live pump task; the TASK OBJECT is stored (strong ref, see
        # _bg_tasks note) and checked with .done() so a crashed/GC'd pump
        # self-heals on the next enqueue instead of stranding the class
        self._direct_pumps: Dict[tuple, object] = {}
        self._direct_conns: Dict[tuple, Connection] = {}
        self._direct_events: Dict[tuple, asyncio.Event] = {}
        # direct actor calls: actor_id -> {"q", "running", "conn"}
        self._actor_direct: Dict[bytes, dict] = {}
        # actor_id -> True when calls are STRICTLY sequential (max_concurrency
        # 1, no concurrency groups): only then may the direct sender batch
        # calls into one frame without changing concurrency semantics
        self._actor_sequential: Dict[bytes, bool] = {}
        # worker-side task-event buffer for direct-push executions
        self._tev_buf: List[dict] = []
        self._tev_flushing = False
        # tick-batched object frees (see _maybe_free)
        self._free_buf: List[bytes] = []
        self._free_flushing = False
        threading.Thread(
            target=self._release_drain_loop,
            name=f"ref-release-{self.client_id[:6]}", daemon=True,
        ).start()
        # --- borrower protocol (ray: reference_count.h:61) ----------------
        # Owned oids pinned by outstanding serialized copies (task args in
        # flight, containment handoffs). Count-based; released when the
        # consuming side has registered as a borrower or finished.
        self._escape_pins: Dict[bytes, int] = {}
        # Owned oid -> set of remote worker addrs currently borrowing it.
        # Each entry has an active wait_ref_removed long-poll task.
        self._borrowers: Dict[bytes, set] = {}
        # Owned container oid -> pin tokens for the refs nested inside it,
        # released when the container is freed (ray: AddNestedObjectIds).
        self._contains: Dict[bytes, list] = {}
        # Borrow-side: oid -> {"count", "owner", "waiters"}; count covers
        # live python refs, serialize-out holds, and containment holds.
        self._borrow_state: Dict[bytes, dict] = {}
        # Container oid -> child oids first borrowed while deserializing it
        # (reported to the container's owner on release for handoff).
        self._borrowed_via: Dict[bytes, set] = {}
        # task_id -> pin tokens for refs serialized into its args.
        self._task_arg_pins: Dict[bytes, list] = {}
        # task_id -> pin tokens for refs serialized into returns we executed,
        # held until the caller acks registration (release_return_pins).
        self._return_pins: Dict[bytes, list] = {}
        # actor_id -> pin tokens for actor-creation args (held until the
        # actor is permanently DEAD: restarts replay the creation spec).
        self._actor_creation_pins: Dict[bytes, list] = {}
        self._actor_sub_done = False
        # --- lineage (ray: object_recovery_manager.h:44) ------------------
        # return oid -> producing TaskSpec (finalized args), for re-execution
        # when the plasma copy is lost. FIFO-capped.
        self._lineage: Dict[bytes, TaskSpec] = {}
        self._reconstructing: Dict[bytes, concurrent.futures.Future] = {}
        self._actor_seq: Dict[bytes, int] = {}
        self._pubsub_handlers: Dict[str, list] = {}
        self.connected = True

    # ------------------------------------------------------------------
    # argument encoding / submitter-side dependency resolution
    # ------------------------------------------------------------------
    def _encode_value(self, value: Any, pins: List) -> Tuple:
        sv = serialization.serialize(value)
        for oid, owner in sv.nested_refs:
            # Refs inside an inlined arg value escape this process: pin them
            # until the consuming task resolves and its executor has
            # registered any kept borrows (ray: reference_count.h arg pins).
            pins.append(self.pin_object(oid, owner))
        if sv.total_data_len <= cfg.max_direct_call_object_size:
            # wire form, not a joined copy: large buffers (numpy/jax host
            # arrays) cross the v2 rpc frame out-of-band, by reference
            return ("v", sv.metadata, sv.to_wire())
        ref = self._put_serialized(sv)
        # Keep the implicit put alive until the consuming task finishes.
        pins.append(self.pin_object(ref.binary(), ref.owner))
        return ("r", ref.binary(), ref.owner)

    def _encode_slots(self, args, kwargs, pins: List):
        """Encode values eagerly; refs become ('pending', ref) placeholders."""
        enc_args = [
            ("pending", a) if isinstance(a, ObjectRef) else self._encode_value(a, pins)
            for a in args
        ]
        enc_kwargs = {
            k: (("pending", v) if isinstance(v, ObjectRef)
                else self._encode_value(v, pins))
            for k, v in (kwargs or {}).items()
        }
        pending = [s[1] for s in enc_args if s[0] == "pending"]
        pending += [s[1] for s in enc_kwargs.values() if s[0] == "pending"]
        return enc_args, enc_kwargs, pending

    def _finalize_slot(self, slot, pins: List):
        if slot[0] != "pending":
            return slot
        ref: ObjectRef = slot[1]
        # Pin for the task's lifetime whether owned (escape pin) or borrowed
        # (our borrow must outlive the handoff to the executor).
        pins.append(self.pin_object(ref.binary(), ref.owner))
        with self._lock:
            inline = self._memory_store.get(ref.binary())
        if inline is not None:
            # Inlining the stored bytes: any refs nested in them stay alive
            # through the pin on the containing object (its _contains pins).
            return ("v", inline[0], inline[1])
        return ("r", ref.binary(), ref.owner or self.addr)

    def _spawn(self, coro) -> "asyncio.Task":
        """create_task + keep a strong reference until completion (asyncio
        keeps only weak refs — see _bg_tasks) + surface dropped
        exceptions."""
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)

        def _done(t):
            self._bg_tasks.discard(t)
            if not t.cancelled() and t.exception() is not None:
                logger.error("background io task failed: %r", t.exception(),
                             exc_info=t.exception())

        task.add_done_callback(_done)
        return task

    async def _submit_when_ready(self, spec: TaskSpec, enc_args, enc_kwargs,
                                 pending: List[ObjectRef], pins: List):
        self._submit_stage[spec.task_id] = "deps_wait"
        try:
            for ref in pending:
                fut = self.future_for(ref)
                await asyncio.wait_for(
                    asyncio.wrap_future(fut), cfg.object_pull_timeout_s * 4
                )
        except Exception as e:
            self._fail_returns(spec, f"dependency resolution failed: {e}")
            return
        self._finish_submit(spec, enc_args, enc_kwargs, pins)

    def _finish_submit(self, spec: TaskSpec, enc_args, enc_kwargs,
                       pins: List):
        """Synchronous tail of submission (deps already resolved). Runs
        directly inside the inbox drain for the common no-deps case — no
        per-call coroutine/task — and from _submit_when_ready otherwise.
        Self-guarding: any failure here fails the task's returns so both
        paths surface errors instead of hanging the caller's get()."""
        try:
            self._finish_submit_inner(spec, enc_args, enc_kwargs, pins)
        except Exception as e:
            logger.exception("submission failed for %s", spec.name)
            self._fail_returns(spec, f"task submission failed: {e!r}")

    def _finish_submit_inner(self, spec: TaskSpec, enc_args, enc_kwargs,
                             pins: List):
        self._submit_stage[spec.task_id] = "finalizing"
        spec.args = [self._finalize_slot(s, pins) for s in enc_args]
        spec.kwargs = {k: self._finalize_slot(s, pins) for k, s in enc_kwargs.items()}
        with self._lock:
            self._task_arg_pins[spec.task_id] = pins
        # Plain DEFAULT-strategy tasks go over worker leases: the raylet
        # grants workers once per burst and tasks push straight to them
        # (2 hops/task instead of 4, no raylet CPU in steady state).
        # Placement-sensitive strategies stay raylet-routed.
        if (cfg.direct_task_leases and spec.actor_id is None
                and spec.scheduling.kind == "DEFAULT"):
            self._submit_stage[spec.task_id] = "direct_enqueued"
            self._direct_enqueue(spec)
            return
        # Actor calls push straight to the actor worker's own endpoint
        # (ray: CoreWorkerDirectActorTaskSubmitter); in-order frames plus
        # the executor's per-caller seq gate preserve call order. Falls
        # back to raylet routing when no direct endpoint is known.
        if (cfg.direct_actor_calls and spec.actor_id is not None
                and not spec.actor_creation):
            self._submit_stage[spec.task_id] = "actor_enqueued"
            self._actor_direct_enqueue(spec)
            return
        # Tick-batched submission: a burst of .remote() calls lands on the
        # io loop as one inbox drain; buffer and ship ONE submit_batch
        # frame (same discipline as the GCS pubsub outbox). Actor tasks
        # ride the same buffer: the buffer is FIFO and the raylet enqueues
        # a batch's actor tasks synchronously in spec order, so per-actor
        # call order survives.
        self._submit_stage[spec.task_id] = "batch_buffered"
        self._submit_buf.append(spec)
        if not self._submit_flushing:
            self._submit_flushing = True
            self._spawn(self._flush_submits())

    def _enqueue_submit(self, spec: TaskSpec, enc_args, enc_kwargs,
                        pending: List[ObjectRef], pins: List):
        """Called from the (sync) submitting thread. One loop wakeup per
        burst: run_coroutine_threadsafe costs ~175us per call (Task +
        cross-thread handle + wakeup-fd write); a deque append plus a
        single coalesced call_soon_threadsafe turns a 1000-task burst's
        1000 wakeups into one."""
        self._submit_inbox.append((spec, enc_args, enc_kwargs, pending, pins))
        with self._inbox_lock:
            if self._inbox_scheduled:
                return
            self._inbox_scheduled = True
        try:
            self.io.loop.call_soon_threadsafe(self._drain_submit_inbox)
        except RuntimeError:
            # loop closed (shutdown race): un-latch so later submissions
            # raise here too instead of silently piling into a dead inbox
            with self._inbox_lock:
                self._inbox_scheduled = False
            raise

    def _drain_submit_inbox(self):
        """On the io loop: drain queued submissions in FIFO order. Specs
        with unresolved deps get a waiter task; the rest route
        synchronously (no coroutine at all). Bounded per callback: a
        producer thread submitting at or above the drain rate must not
        starve the loop's other callbacks (socket flushes, result
        delivery), so only the entries present at entry are drained and a
        fresh callback is scheduled for any remainder."""
        with self._inbox_lock:
            self._inbox_scheduled = False
        for _ in range(len(self._submit_inbox)):
            try:
                spec, enc_args, enc_kwargs, pending, pins = \
                    self._submit_inbox.popleft()
            except IndexError:
                break
            try:
                if pending:
                    self._spawn(self._submit_when_ready(
                        spec, enc_args, enc_kwargs, pending, pins
                    ))
                else:
                    self._finish_submit(spec, enc_args, enc_kwargs, pins)
            except Exception as e:
                logger.exception("submission failed for %s", spec.name)
                self._fail_returns(spec, f"task submission failed: {e!r}")
        if self._submit_inbox:
            with self._inbox_lock:
                if self._inbox_scheduled:
                    return
                self._inbox_scheduled = True
            asyncio.get_running_loop().call_soon(self._drain_submit_inbox)

    async def _flush_submits(self):
        await asyncio.sleep(0)  # one tick: let same-burst submissions land
        batch, self._submit_buf = self._submit_buf, []
        self._submit_flushing = False
        if not batch:
            return
        payload = {"specs": batch}
        if cfg.submit_ack_mode == "batch":
            # fire-and-forget lane: the raylet acks frame ACCEPTANCE and
            # schedules in the background; per-task failures surface via
            # the owner's task_result stream + task events, so this await
            # no longer spans per-spec scheduling
            payload["ack"] = "batch"
        try:
            # retried with backoff; the idem token is keyed on the FULL
            # frame (first, last, len): a frame is identified by its exact
            # spec run, so a retry never aliases a different batch that
            # merely shares its head (the old first-spec-only key deduped
            # a grown/regrouped retry frame wrong)
            await call_with_retries(
                lambda: self.raylet, "submit_batch", payload,
                idem=("submit_batch", batch[0].task_id, batch[-1].task_id,
                      len(batch), batch[0].attempt),
            )
            for spec in batch:
                self._submit_stage[spec.task_id] = "raylet_accepted"
        except Exception as e:
            for spec in batch:
                self._fail_returns(spec, f"task submission failed: {e}")

    # -- direct task push over worker leases ---------------------------
    def _observe_direct_placement(self, batch):
        """Stamp ready->push latency for direct-push specs (the direct
        half of the two-path placement-latency histogram)."""
        now = time.perf_counter()
        hist = self._direct_placement_lat
        if hist is None:
            from ray_tpu._private import metrics_core as mc

            hist = self._direct_placement_lat = mc.registry().histogram(
                "raylet_task_placement_latency_seconds",
                "Task ready to dispatched-to-worker, by dispatch path",
                scale=mc.LATENCY,
            ).labels(node=self.node_id[:12], path="direct")
        for spec in batch:
            t0 = self._direct_ready_at.pop(spec.task_id, None)
            if t0 is not None:
                hist.record(now - t0)

    def _drop_direct_stamps(self, batch):
        for spec in batch:
            self._direct_ready_at.pop(spec.task_id, None)

    def _direct_enqueue(self, spec: TaskSpec):
        key = (tuple(sorted(spec.resources.items())), repr(spec.runtime_env))
        self._direct_ready_at[spec.task_id] = time.perf_counter()
        self._direct_q.setdefault(key, deque()).append(spec)
        ev = self._direct_events.get(key)
        if ev is None:
            ev = self._direct_events[key] = asyncio.Event()
        ev.set()
        t = self._direct_pumps.get(key)
        if t is None or t.done():
            self._direct_pumps[key] = self._spawn(self._direct_pump(key))

    async def _direct_pump(self, key: tuple):
        """One pump per scheduling class: lease workers from the raylet,
        fan feeders over the leases, and HOLD the leases across bursts —
        when the class queue drains, the pump keeps its grant warm for
        direct_lease_grace_s (grace-period return) so a sequential
        submit→get loop's next call rides the already-open lease conns
        with zero raylet round trips instead of re-leasing per burst.
        Each burst tops the grant up toward the queue-depth ask (lease
        prefetch: the held leases are already in hand before the lease
        RPC for the delta returns). Zero grants (no local capacity /
        feature off on the raylet) falls back to raylet-routed
        submission, which spills across nodes as usual."""
        q = self._direct_q[key]
        held: List[dict] = []
        try:
            while True:
                if not q:
                    if not held or cfg.direct_lease_grace_s <= 0:
                        break
                    # grace window: keep the grant warm for the next burst
                    ev = self._direct_events[key]
                    ev.clear()
                    if q:  # a spec landed between the check and the clear
                        continue
                    try:
                        await asyncio.wait_for(
                            ev.wait(), cfg.direct_lease_grace_s
                        )
                    except asyncio.TimeoutError:
                        break
                    continue
                spec0 = q[0]
                depth = cfg.direct_lease_pipeline_depth
                want = min(cfg.direct_lease_max,
                           max(1, (len(q) + depth - 1) // depth))
                spillable = False
                if len(held) < want:
                    try:
                        reply = await self.raylet.request(
                            "lease_workers",
                            {"resources": dict(spec0.resources),
                             "runtime_env": spec0.runtime_env,
                             "job_id": self.job_id,
                             "count": want - len(held)},
                        )
                        held.extend(reply.get("leases") or [])
                        spillable = bool(reply.get("spillable"))
                    except Exception:
                        pass
                if not held:
                    batch = list(q)
                    q.clear()
                    self._drop_direct_stamps(batch)
                    try:
                        await self.raylet.request(
                            "submit_batch", {"specs": batch}
                        )
                        for s in batch:
                            self._submit_stage[s.task_id] = "raylet_no_lease"
                    except Exception as e:
                        for s in batch:
                            self._fail_returns(
                                s, f"task submission failed: {e}"
                            )
                    continue
                # Local leases can't absorb an arbitrarily deep queue —
                # but detouring the tail through the raylet only helps
                # when that reaches capacity BEYOND these leases: on a
                # multi-node cluster (reply.spillable) whose local grant
                # is the bottleneck — fewer granted than asked, or the
                # ask itself clamped at direct_lease_max. An unclamped
                # full grant just means the burst outran the ask (the
                # submit drain races the lease round trip), and on a
                # single node the raylet would dispatch to the same
                # workers via the slow path — either way the queue stays
                # on the direct pipelines, where feeders amortize via
                # spec batching and the pump re-leases next iteration.
                cap = len(held) * depth * 8
                local_limit = (len(held) < want
                               or want >= cfg.direct_lease_max)
                if (local_limit and spillable
                        and len(q) > cap):
                    tail = [q.pop() for _ in range(len(q) - cap)]
                    tail.reverse()
                    self._drop_direct_stamps(tail)
                    try:
                        await self.raylet.request(
                            "submit_batch", {"specs": tail}
                        )
                        for s in tail:
                            self._submit_stage[s.task_id] = "raylet_spill"
                    except Exception as e:
                        for s in tail:
                            self._fail_returns(
                                s, f"task submission failed: {e}"
                            )
                ev = self._direct_events[key]
                # one LINGERING feeder per lease; the rest exit on drain.
                # A sync call loop then pays one event wakeup per call
                # instead of a thundering herd of `depth` waiters, while
                # burst capacity (depth in-flight per lease) is restored
                # by the pump respawning the full fan on the next round.
                feeders = [
                    self._spawn(self._direct_feed(lease, q, ev,
                                                  linger=(j == 0)))
                    for lease in held for j in range(depth)
                ]
                # return_exceptions: one crashed feeder must not kill the
                # pump before the leases are returned — a dead pump strands
                # the lease's reserved CPU and every spec still queued.
                for res in await asyncio.gather(
                    *feeders, return_exceptions=True
                ):
                    if isinstance(res, BaseException):
                        logger.error("direct feeder crashed: %r", res,
                                     exc_info=res)
        finally:
            for lease in held:
                try:
                    await self.raylet.notify(
                        "return_lease", {"lease_id": lease["lease_id"]}
                    )
                except Exception:
                    pass
            self._direct_pumps.pop(key, None)
            if q:  # a burst landed during the finally window: restart
                self._direct_pumps[key] = self._spawn(self._direct_pump(key))
            else:
                self._direct_q.pop(key, None)

    async def _direct_conn(self, lease: dict) -> Optional[Connection]:
        ep = (lease["host"], lease["port"])
        conn = self._direct_conns.get(ep)
        if conn is not None and not conn.closed:
            return conn
        try:
            conn = await connect(ep[0], ep[1], handler=self,
                                 name=f"direct:{ep[1]}", retries=2)
        except Exception:
            return None
        self._direct_conns[ep] = conn
        return conn

    async def _direct_feed(self, lease: dict, q: deque, ev: asyncio.Event,
                           linger: bool = True):
        conn = await self._direct_conn(lease)
        # hotpath: begin direct_feed (per-spec stamps are precomputed —
        # no per-call string formatting on the steady-state push path)
        pushed_stage = "pushed:%d" % lease["port"]
        while True:
            if not q:
                if not linger:
                    return  # non-lingering feeder: exit on drain
                # linger: a sequential submit-get loop reuses the standing
                # lease (2 hops/call) instead of re-leasing per call
                ev.clear()
                if q:  # a spec landed between the check and the clear
                    ev.set()
                    continue
                try:
                    await asyncio.wait_for(
                        ev.wait(), cfg.direct_lease_linger_s
                    )
                except asyncio.TimeoutError:
                    return
                continue
            # Adaptive batching: take whatever burst accumulated while the
            # previous round-trip was in flight (one spec when idle — same
            # latency as the unbatched path; a deep queue amortizes the
            # per-message frame/dispatch cost across up to batch_max specs).
            k = min(len(q), cfg.direct_push_batch_max)
            batch = [q.popleft() for _ in range(k)]
            if conn is None or conn.closed:
                # endpoint gone BEFORE anything was sent: the tasks never
                # started, so reroute via the raylet without consuming a
                # retry attempt (at-most-once was never at risk)
                self._drop_direct_stamps(batch)
                try:
                    await self.raylet.request(
                        "submit_batch", {"specs": batch}
                    )
                    for spec in batch:
                        self._submit_stage[spec.task_id] = "raylet_reroute"
                except Exception as e:
                    for spec in batch:
                        self._fail_returns(
                            spec, f"task submission failed: {e}"  # lint: allow-hotpath (reroute error path)
                        )
                return
            for spec in batch:
                self._submit_stage[spec.task_id] = pushed_stage
            self._observe_direct_placement(batch)
            # hotpath: end direct_feed
            try:
                # timeout=0 (unbounded): these awaits span the USER CODE's
                # runtime — a deadline would falsely fail long tasks.
                # Keepalive detects the dead-worker case instead.
                if len(batch) == 1:
                    results = [await conn.request(
                        "execute_task", {"spec": batch[0]}, timeout=0
                    )]
                else:
                    # batch results STREAM back as task_result notifies as
                    # each task finishes (so ray.wait sees early tasks);
                    # the response is only the completion ack
                    await conn.request(
                        "execute_task_batch", {"specs": batch}, timeout=0
                    )
                    results = None
            except Exception:
                for spec in batch:
                    with self._lock:
                        # a streamed result may have landed (and popped the
                        # inflight record) before the connection died —
                        # re-running THAT task would double-execute it
                        still_pending = spec.task_id in self._specs_inflight
                    if not still_pending:
                        continue
                    self._submit_stage[spec.task_id] = "worker_lost"
                    try:
                        await self._direct_worker_lost(spec, lease)
                    except Exception:
                        logger.exception(
                            "direct-push loss handling failed for %s",
                            spec.name,
                        )
                        self._fail_returns_exc(
                            spec, WorkerDiedError("leased worker lost")
                        )
                return
            if results is None:
                continue  # batch path: results already streamed + processed
            # The spec is consumed from the queue: any failure past this
            # point MUST still resolve the task's returns, or the caller's
            # get() blocks forever on an object nobody will produce.
            for spec, result in zip(batch, results):
                self._submit_stage[spec.task_id] = "resulted"
                try:
                    await self._direct_result(spec, result)
                except Exception as e:
                    logger.exception(
                        "direct result processing failed for %s", spec.name
                    )
                    self._fail_returns(
                        spec, f"internal error processing task result: {e!r}"
                    )

    # -- direct actor calls --------------------------------------------
    def _actor_direct_enqueue(self, spec: TaskSpec):
        st = self._actor_direct.setdefault(
            spec.actor_id,
            {"q": deque(), "running": False, "conn": None,
             "fallback": False, "inflight": 0, "relost": [],
             "settled": asyncio.Event(), "wake": asyncio.Event()},
        )
        st["q"].append(spec)
        st["wake"].set()  # rouse a lingering sender
        if not st["running"]:
            st["running"] = True
            self._spawn(self._actor_sender(spec.actor_id, st))

    async def _actor_sender(self, actor_id: bytes, st: dict):
        """Single sender per actor: pipelined in-order request_nowait
        pushes over one connection (wire order = call order; replies are
        awaited concurrently).

        Ordering across failures: once ANY call for this actor has been
        routed via the raylet (direct endpoint unavailable, or a direct
        conn broke mid-burst), the actor goes into STICKY raylet fallback.
        Mixing routes would let a later seq overtake an earlier one in the
        restart window, and the fresh executor's seq gate would anchor on
        the wrong call. Recovery waits for every in-flight direct reply to
        settle, then resubmits the failed calls lowest-seq-first ahead of
        anything still queued."""
        # one tick before draining: under the eager task factory the sender
        # would otherwise run synchronously inside the FIRST enqueue of a
        # burst and see a one-deep queue (no batching, one frame per call)
        await asyncio.sleep(0)
        loop = asyncio.get_running_loop()
        try:
            while True:
                if not (st["q"] or st["relost"]):
                    # linger on drain: a sync call loop reuses this sender
                    # (and its pipelined conn + warm-up tick) instead of
                    # paying a task spawn per call; the enqueue path sets
                    # st["wake"] to rouse it
                    if cfg.actor_sender_linger_s <= 0:
                        return
                    wake = st["wake"]
                    wake.clear()
                    if st["q"] or st["relost"]:
                        continue  # raced an enqueue between check and clear
                    try:
                        await asyncio.wait_for(
                            wake.wait(), cfg.actor_sender_linger_s
                        )
                    except asyncio.TimeoutError:
                        return  # finally respawns if an enqueue raced this
                    continue
                if st["fallback"]:
                    # collect every outcome before rerouting so the raylet
                    # sees the calls in seq order
                    while st["inflight"]:
                        st["settled"].clear()
                        await st["settled"].wait()
                    relost, st["relost"] = st["relost"], []
                    relost.sort(key=lambda s: s.seq_no)
                    batch = relost + list(st["q"])
                    st["q"].clear()
                    if not batch:
                        continue
                    try:
                        await self.raylet.request(
                            "submit_batch", {"specs": batch}
                        )
                    except Exception as e:
                        for s in batch:
                            self._fail_returns(
                                s, f"task submission failed: {e}"
                            )
                    continue
                conn = st["conn"]
                if conn is None or conn.closed:
                    # never dial a new incarnation while old in-flight
                    # calls are unsettled: the new conn could deliver a
                    # later seq before the earlier seq's failure rerouted
                    while st["inflight"]:
                        st["settled"].clear()
                        await st["settled"].wait()
                    if st["fallback"]:
                        continue
                    conn = await self._actor_direct_connect(actor_id)
                    st["conn"] = conn
                    if conn is None:
                        st["fallback"] = True
                        continue
                if self._actor_sequential.get(actor_id):
                    # Strictly sequential actor: a burst may ride ONE
                    # frame/dispatch without changing call semantics. Cap
                    # frames in flight so the NEXT burst accumulates into a
                    # real batch instead of leaving one spec at a time
                    # (a submitting thread slower than this loop would
                    # otherwise never see queue depth > 1).
                    while (st["inflight"] >= cfg.actor_direct_max_inflight
                           and not st["fallback"]
                           and st["conn"] is conn and not conn.closed):
                        st["settled"].clear()
                        await st["settled"].wait()
                    if (st["fallback"] or st["conn"] is not conn
                            or conn.closed):
                        continue  # re-evaluate route from the loop top
                    if not st["q"]:
                        continue
                    k = min(len(st["q"]), cfg.direct_push_batch_max)
                    batch = [st["q"].popleft() for _ in range(k)]
                else:
                    batch = [st["q"].popleft()]
                try:
                    if len(batch) == 1:
                        fut = conn.request_nowait(
                            "execute_task", {"spec": batch[0]}
                        )
                    else:
                        fut = conn.request_nowait(
                            "execute_task_batch", {"specs": batch}
                        )
                except Exception:
                    st["conn"] = None
                    st["fallback"] = True
                    st["relost"].extend(batch)
                    continue
                st["inflight"] += 1
                self._spawn(
                    self._actor_direct_reply(actor_id, st, batch, fut)
                )
        finally:
            st["running"] = False
            if (st["q"] or st["relost"]) and not st["running"]:
                st["running"] = True
                self._spawn(self._actor_sender(actor_id, st))

    async def _actor_direct_connect(self, actor_id: bytes):
        try:
            table = await self.gcs.request(
                "wait_actor_alive",
                {"actor_id": actor_id,
                 "timeout": cfg.actor_route_wait_alive_timeout_s},
            )
        except Exception:
            return None
        if (not table or table.get("state") != "ALIVE"
                or not table.get("direct_addr")):
            return None
        host, port = table["direct_addr"]
        try:
            return await connect(host, port, handler=self,
                                 name=f"actor-direct:{port}", retries=2)
        except Exception:
            return None

    async def _actor_direct_reply(self, actor_id: bytes, st: dict,
                                  batch: List[TaskSpec], fut):
        try:
            results = await fut
            # batch replies are completion acks — the per-call results
            # streamed back as task_result notifies while the batch ran
            results = [results] if len(batch) == 1 else None
        except Exception:
            # Worker died / restarting: flip to sticky raylet fallback. The
            # calls were SENT, so their fate is unknown — at-most-once actor
            # semantics (ray: actor tasks are NOT retried unless
            # max_task_retries is set) forbid blind resubmission: a
            # side-effecting call like `die()` would re-execute against the
            # restarted incarnation and burn its max_restarts budget.
            st["fallback"] = True
            if st.get("conn") is not None and st["conn"].closed:
                st["conn"] = None
            for spec in batch:
                with self._lock:
                    # a streamed result may have landed before the failure;
                    # re-submitting THAT call would break at-most-once
                    still_pending = spec.task_id in self._specs_inflight
                if not still_pending:
                    continue
                if spec.attempt < spec.max_retries:
                    spec.attempt += 1
                    st["relost"].append(spec)
                else:
                    self._fail_returns_exc(spec, ActorDiedError(
                        f"The actor died while this call was in flight; "
                        f"actor tasks run at-most-once and are not retried "
                        f"unless max_task_retries is set "
                        f"(method {spec.name!r})."
                    ))
            st["inflight"] -= 1
            st["settled"].set()
            if not st["running"]:
                st["running"] = True
                self._spawn(self._actor_sender(actor_id, st))
            return
        st["inflight"] -= 1
        st["settled"].set()
        if results is None:
            return  # batch path: results already streamed + processed
        for spec, result in zip(batch, results):
            try:
                await self._direct_result(spec, result)
            except Exception as e:
                logger.exception(
                    "actor-direct result processing failed for %s", spec.name
                )
                self._fail_returns(
                    spec, f"internal error processing task result: {e!r}"
                )

    async def _direct_worker_lost(self, spec: TaskSpec,
                                  lease: Optional[dict] = None):
        """Leased worker died/unreachable mid-push: resolve WHY from the
        raylet (e.g. an OOM kill must surface as such, not as a generic
        connection loss), then feed the standard failure path (it retries
        via the raylet when retriable)."""
        reason = "leased worker lost"
        if lease and lease.get("worker_id"):
            for _ in range(3):
                try:
                    fate = await self.raylet.request(
                        "worker_fate", {"client_id": lease["worker_id"]}
                    )
                except Exception:
                    break
                if fate.get("reason"):
                    reason = fate["reason"]
                    break
                if not fate.get("alive"):
                    break
                # raylet hasn't processed the worker's death yet
                await asyncio.sleep(0.1)
        await self.rpc_task_result(self.raylet, {
            "task_id": spec.task_id, "results": None,
            "error": reason, "system_error": True, "worker_died": True,
            "retriable": True, "attempt": spec.attempt,
        })

    async def _direct_result(self, spec: TaskSpec, result: dict):
        """Adapt the executor's result dict into the task_result payload
        the raylet would have delivered (raylet._deliver_result shape);
        stored-object locations were self-reported by the worker."""
        await self.rpc_task_result(self.raylet, {
            "task_id": spec.task_id,
            "results": result.get("results"),
            "error": result.get("error"),
            "error_value": result.get("error_value"),
            "app_error": result.get("app_error", False),
            "retriable": result.get("retriable", False),
            "attempt": spec.attempt,
            "exec_addr": result.get("exec_addr"),
            "borrows_kept": result.get("borrows_kept"),
            "returns_nested": result.get("returns_nested"),
            "dynamic_return_oids": result.get("dynamic_return_oids"),
        })

    def _release_task_pins(self, task_id: bytes):
        with self._lock:
            pins = self._task_arg_pins.pop(task_id, None)
        for token in pins or ():
            self.unpin_object(token)

    def _fail_returns(self, spec: TaskSpec, message: str):
        self._fail_returns_exc(spec, RuntimeError(message))

    def _fail_returns_exc(self, spec: TaskSpec, exc: Exception):
        sv = serialization.serialize_error(exc, spec.name)
        tid = TaskID(spec.task_id)
        self._submit_stage.pop(spec.task_id, None)
        with self._lock:
            self._specs_inflight.pop(spec.task_id, None)
        for i in range(max(1, spec.num_returns)):
            oid = ObjectID.from_index(tid, i + 1)
            self._resolve_inline(oid.binary(), sv.metadata, sv.to_wire())
        self._fail_dynamic_item_futures(spec, sv)
        self._release_task_pins(spec.task_id)

    def _fail_dynamic_item_futures(self, spec: Optional[TaskSpec], sv):
        """A failed dynamic task must also resolve any ITEM futures parked
        by reconstruction (their indices aren't enumerable from
        num_returns): sweep pending futures keyed by this task's prefix."""
        if spec is None or spec.num_returns != -1:
            return
        prefix = spec.task_id
        with self._lock:
            pending = [
                oid for oid, f in self._futures.items()
                if oid.startswith(prefix) and not f.done()
            ]
        for oid in pending:
            self._resolve_inline(oid, sv.metadata, sv.to_wire())

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def task_template(
        self,
        func=None,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        scheduling: Optional[SchedulingStrategy] = None,
        max_retries: int = 3,
        retry_exceptions: bool = False,
        name: str = "",
        func_blob: Optional[bytes] = None,
        runtime_env: Optional[dict] = None,
    ) -> TaskTemplate:
        """Build the immutable submit template for a plain-task callsite:
        the constant half of submit_task, paid once per (RemoteFunction,
        options, worker) instead of per call."""
        import cloudpickle

        t = TaskTemplate()
        t.worker = self
        scheduling = scheduling or SchedulingStrategy()
        res = dict(resources if resources is not None else {"CPU": 1.0})
        if scheduling.kind == "PLACEMENT_GROUP":
            res = rewrite_resources_for_pg(
                res, scheduling.pg_id, scheduling.pg_bundle_index
            )
        t.resources = res
        t.scheduling = scheduling
        t.name = name or getattr(func, "__name__", "task")
        t.func_blob = (func_blob if func_blob is not None
                       else cloudpickle.dumps(func))
        t.method_name = None
        t.num_returns = num_returns
        t.max_retries = max_retries
        t.retry_exceptions = retry_exceptions
        t.runtime_env = runtime_env
        t.actor_id = None
        t.concurrency_group = None
        t.minter = TaskIDMinter.for_job(JobID(self.job_id))
        return t

    def actor_task_template(
        self,
        actor_id: bytes,
        method_name: str,
        num_returns: int = 1,
        max_task_retries: int = 0,
        concurrency_group: Optional[str] = None,
    ) -> TaskTemplate:
        """Submit template for one actor method callsite (the constant
        half of submit_actor_task)."""
        t = TaskTemplate()
        t.worker = self
        t.name = method_name
        t.method_name = method_name
        t.func_blob = None
        t.num_returns = num_returns
        t.resources = {}
        t.scheduling = None
        t.max_retries = max_task_retries
        t.retry_exceptions = False
        t.runtime_env = None
        t.actor_id = actor_id
        t.concurrency_group = concurrency_group
        t.minter = TaskIDMinter.for_actor(ActorID(actor_id))
        return t

    # hotpath: begin submit (lint_hotpath: no per-call dict( copies or
    # f-string id minting — constant work belongs in the template)
    def submit_from_template(self, tmpl: TaskTemplate, args,
                             kwargs) -> List[ObjectRef]:
        """Per-call half of plain-task submission: mint an id from the
        template's block minter, encode the arguments, stamp the spec."""
        timed = cfg.control_plane_stage_timing
        t0 = time.perf_counter() if timed else 0.0
        task_id = tmpl.minter.next_binary()
        if timed:
            _stage_record("id_mint", time.perf_counter() - t0)
        pins: List = []
        enc_args, enc_kwargs, pending = self._encode_slots(args, kwargs, pins)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=tmpl.name,
            func_blob=tmpl.func_blob,
            method_name=None,
            num_returns=tmpl.num_returns,
            resources=tmpl.resources,
            scheduling=tmpl.scheduling,
            owner=self.addr,
            max_retries=tmpl.max_retries,
            retry_exceptions=tmpl.retry_exceptions,
            caller_id=self._caller_id,
            runtime_env=tmpl.runtime_env,
            tracing_ctx=_tracing_ctx(),
        )
        refs = self._register_returns(spec)
        self._enqueue_submit(spec, enc_args, enc_kwargs, pending, pins)
        if timed:
            _stage_record("envelope_build", time.perf_counter() - t0)
        return refs

    def submit_actor_from_template(self, tmpl: TaskTemplate, args,
                                   kwargs) -> List[ObjectRef]:
        """Per-call half of actor-task submission: mint, stamp the seq,
        encode, enqueue."""
        timed = cfg.control_plane_stage_timing
        t0 = time.perf_counter() if timed else 0.0
        task_id = tmpl.minter.next_binary()
        if timed:
            _stage_record("id_mint", time.perf_counter() - t0)
        actor_id = tmpl.actor_id
        with self._lock:
            seq = self._actor_seq.get(actor_id, 0)
            self._actor_seq[actor_id] = seq + 1
        pins: List = []
        enc_args, enc_kwargs, pending = self._encode_slots(args, kwargs, pins)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=tmpl.name,
            func_blob=None,
            method_name=tmpl.method_name,
            num_returns=tmpl.num_returns,
            resources=tmpl.resources,
            owner=self.addr,
            actor_id=actor_id,
            max_retries=tmpl.max_retries,
            seq_no=seq,
            caller_id=self._caller_id,
            tracing_ctx=_tracing_ctx(),
            concurrency_group=tmpl.concurrency_group,
        )
        refs = self._register_returns(spec)
        self._enqueue_submit(spec, enc_args, enc_kwargs, pending, pins)
        if timed:
            _stage_record("envelope_build", time.perf_counter() - t0)
        return refs
    # hotpath: end submit

    def submit_task(
        self,
        func,
        args=(),
        kwargs=None,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        scheduling: Optional[SchedulingStrategy] = None,
        max_retries: int = 3,
        retry_exceptions: bool = False,
        name: str = "",
        func_blob: Optional[bytes] = None,
        runtime_env: Optional[dict] = None,
    ) -> List[ObjectRef]:
        """One-shot submission (no callsite cache): builds a throwaway
        template. The API layer's RemoteFunction caches the template and
        calls submit_from_template directly."""
        tmpl = self.task_template(
            func=func, num_returns=num_returns, resources=resources,
            scheduling=scheduling, max_retries=max_retries,
            retry_exceptions=retry_exceptions, name=name,
            func_blob=func_blob, runtime_env=runtime_env,
        )
        return self.submit_from_template(tmpl, args, kwargs)

    # hotpath: begin register_returns
    def _register_returns(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = []
        task_binary = spec.task_id
        addr = self.addr
        # dynamic (-1): one visible return — the ref-list; item objects are
        # adopted at result time (rpc_task_result dynamic_return_oids)
        n = 1 if spec.num_returns == -1 else spec.num_returns
        with self._lock:
            self._specs_inflight[task_binary] = spec
            for i in range(n):
                ob = object_id_binary(task_binary, i + 1)
                fut = concurrent.futures.Future()
                self._futures[ob] = fut
                self._owned.add(ob)
                refs.append(ObjectRef(ObjectID(ob), addr))
        for r in refs:
            self.add_local_ref(r)
        return refs
    # hotpath: end register_returns

    # -- actors ---------------------------------------------------------
    def create_actor(
        self,
        cls,
        args,
        kwargs,
        resources: Dict[str, float],
        scheduling: Optional[SchedulingStrategy] = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: int = 1,
        concurrency_groups: Optional[Dict[str, int]] = None,
        lifetime: Optional[str] = None,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        runtime_env: Optional[dict] = None,
    ) -> bytes:
        import cloudpickle

        actor_id = ActorID.of(JobID(self.job_id))
        self._actor_sequential[actor_id.binary()] = (
            max_concurrency == 1 and not concurrency_groups
        )
        resources = dict(resources)
        scheduling = scheduling or SchedulingStrategy()
        if scheduling.kind == "PLACEMENT_GROUP":
            resources = rewrite_resources_for_pg(
                resources, scheduling.pg_id, scheduling.pg_bundle_index
            )
        pins: List = []
        enc_args, enc_kwargs, pending = self._encode_slots(args, kwargs, pins)
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(actor_id).binary(),
            job_id=self.job_id,
            name=getattr(cls, "__name__", "Actor"),
            func_blob=cloudpickle.dumps(cls),
            method_name=None,
            resources=resources,
            scheduling=scheduling,
            owner=self.addr,
            actor_id=actor_id.binary(),
            actor_creation=True,
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            max_concurrency=max_concurrency,
            concurrency_groups=dict(concurrency_groups or {}),
            lifetime=lifetime,
            name_registered=name,
            namespace=namespace or self.namespace,
            runtime_env=runtime_env,
            caller_id=self.client_id.encode(),
        )
        if not pending:
            spec.args = [self._finalize_slot(s, pins) for s in enc_args]
            spec.kwargs = {k: self._finalize_slot(s, pins)
                           for k, s in enc_kwargs.items()}
            self._hold_actor_creation_pins(actor_id.binary(), pins)
            # side-effectful: the actor_id itself is the idempotency token,
            # so a retried registration can't double-register the actor
            reply = self.io.run(
                call_with_retries(
                    lambda: self.gcs, "register_actor", {"spec": spec},
                    timeout=cfg.gcs_rpc_timeout_s,
                    idem=("register_actor", actor_id.binary()),
                ),
                # outer bound > worst-case inner (attempts x (rpc + backoff))
                timeout=(cfg.gcs_rpc_timeout_s + cfg.rpc_retry_max_delay_s)
                * cfg.rpc_retry_attempts + 5.0,
            )
            if reply.get("error"):
                raise ValueError(reply["error"])
        else:
            self.io.call_soon(
                self._register_actor_when_ready(
                    spec, enc_args, enc_kwargs, pending, pins
                )
            )
        return actor_id.binary()

    async def _register_actor_when_ready(self, spec, enc_args, enc_kwargs,
                                         pending, pins):
        for ref in pending:
            try:
                await asyncio.wait_for(
                    asyncio.wrap_future(self.future_for(ref)),
                    cfg.object_pull_timeout_s * 4,
                )
            except Exception:
                logger.error("actor %s creation dependency failed", spec.name)
        spec.args = [self._finalize_slot(s, pins) for s in enc_args]
        spec.kwargs = {k: self._finalize_slot(s, pins) for k, s in enc_kwargs.items()}
        self._hold_actor_creation_pins(spec.actor_id, pins)
        await call_with_retries(
            lambda: self.gcs, "register_actor", {"spec": spec},
            idem=("register_actor", spec.actor_id),
        )

    def _hold_actor_creation_pins(self, actor_id: bytes, pins: List):
        """Actor-creation args must survive restarts: the GCS replays the
        creation spec on failure, so the pins are held until the actor is
        permanently DEAD (ray: gcs_actor_manager.h lineage of creation spec)."""
        if not pins:
            return
        with self._lock:
            self._actor_creation_pins[actor_id] = pins
        if not self._actor_sub_done:
            self._actor_sub_done = True
            # Register the handler synchronously and schedule the GCS
            # subscribe as a loop task: this may run ON the io loop
            # (_register_actor_when_ready), where a blocking io.run would
            # deadlock the loop against itself.
            self._pubsub_handlers.setdefault("actor", []).append(self._on_actor_event)
            self.io.call_soon(self.gcs.request("subscribe", {"channel": "actor"}))

    def _on_actor_event(self, table: dict):
        if table.get("state") != "DEAD":
            return
        with self._lock:
            pins = self._actor_creation_pins.pop(table.get("actor_id"), None)
        for token in pins or ():
            self.unpin_object(token)

    def submit_actor_task(
        self,
        actor_id: bytes,
        method_name: str,
        args=(),
        kwargs=None,
        num_returns: int = 1,
        max_task_retries: int = 0,
        concurrency_group: Optional[str] = None,
    ) -> List[ObjectRef]:
        """One-shot actor submission (no callsite cache); ActorMethod
        caches a template and calls submit_actor_from_template directly."""
        tmpl = self.actor_task_template(
            actor_id, method_name, num_returns=num_returns,
            max_task_retries=max_task_retries,
            concurrency_group=concurrency_group,
        )
        return self.submit_actor_from_template(tmpl, args, kwargs)

    def get_actor_table(self, actor_id: Optional[bytes] = None,
                        name: Optional[str] = None, namespace: Optional[str] = None):
        return self.io.run(
            self.gcs.request(
                "get_actor",
                {"actor_id": actor_id, "name": name,
                 "namespace": namespace or self.namespace},
            )
        )

    def wait_actor_alive(self, actor_id: bytes, timeout: float = 60.0):
        return self.io.run(
            self.gcs.request("wait_actor_alive",
                             {"actor_id": actor_id, "timeout": timeout})
        )

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self.io.run(
            self.gcs.request("kill_actor", {"actor_id": actor_id, "no_restart": no_restart})
        )

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        task_id = ref.id().task_id()
        self.io.run(
            self.raylet.request("cancel_task", {"task_id": task_id.binary(), "force": force})
        )

    # ------------------------------------------------------------------
    # owner notifications (results arrive here)
    # ------------------------------------------------------------------
    # -- ownership-based object directory ------------------------------
    async def rpc_object_locations(self, conn: Connection, p):
        """Location lookup served by the OWNER (ray:
        ownership_based_object_directory.h) — raylets resolve here first,
        GCS directory second."""
        oid = p["object_id"]
        with self._lock:
            locs = set(self._owned_locations.get(oid, ()))
        if object_store.object_exists(self.store_dir, ObjectID(oid)):
            locs.add(self.node_id)
        return {"locations": list(locs)}

    def rpc_owner_add_location(self, conn: Connection, p):
        """A raylet created/received a copy of an object we own."""
        with self._lock:
            if p["object_id"] in self._owned:
                self._owned_locations.setdefault(
                    p["object_id"], set()
                ).add(p["node_id"])

    def rpc_owner_remove_location(self, conn: Connection, p):
        """A raylet found our recorded copy unreachable/gone: retract it
        so the directory converges (there is no eviction protocol)."""
        with self._lock:
            locs = self._owned_locations.get(p["object_id"])
            if locs is not None:
                locs.discard(p["node_id"])

    def _record_owned_location(self, oid: bytes, node_id: Optional[str]):
        if not node_id:
            return
        with self._lock:
            self._owned_locations.setdefault(oid, set()).add(node_id)

    async def rpc_task_result_batch(self, conn: Connection, payloads):
        """Tick-batched completions from the raylet (one frame per burst;
        see raylet._flush_owner_outbox)."""
        for p in payloads:
            await self.rpc_task_result(conn, p)

    async def rpc_task_result(self, conn: Connection, p):
        t0 = (time.perf_counter()
              if cfg.control_plane_stage_timing else 0.0)
        task_id: bytes = p["task_id"]
        with self._lock:
            spec = self._specs_inflight.get(task_id)
            if spec is not None and p.get("attempt", 0) < spec.attempt:
                return  # stale notification from a superseded attempt
        if p.get("error") is not None:
            await self._handle_task_error(spec, task_id, p)
            return
        results = p["results"] or []
        self._submit_stage.pop(task_id, None)
        with self._lock:
            self._specs_inflight.pop(task_id, None)
        # num_returns="dynamic": adopt ownership of the item objects BEFORE
        # the ref-list materializes (deserializing it registers refs, which
        # must find their oids in _owned), record their lineage so a lost
        # item re-executes this task, and pin each under the ref-list
        # container so dropping the (possibly never-materialized) list
        # frees the items (_maybe_free releases _contains pins).
        dyn_oids = p.get("dynamic_return_oids") or ()
        # Adopt only on the first (spec-bearing) delivery: the spilled-task
        # at-least-once resubmission path can deliver task_result twice, and
        # re-adopting would re-pin items under a ref-list that may already
        # have been freed, leaking escape pins.
        exec_node = (p.get("exec_addr") or (None,))[0]
        if dyn_oids and spec is not None:
            list_oid = object_id_binary(task_id, 1)
            tokens = []
            for oid in dyn_oids:
                with self._lock:
                    self._owned.add(oid)
                    if spec is not None:
                        self._lineage_insert_locked(oid, spec)
                self._record_owned_location(oid, exec_node)
                tokens.append(self.pin_object(oid, self.addr))
                # a reconstruction (or wait) may be parked on this item
                self._resolve_plasma(oid)
            with self._lock:
                self._contains.setdefault(list_oid, []).extend(tokens)
        # hotpath: begin task_result_resolve (raw oid binaries — no ID
        # object churn on the per-result resolve path)
        for i, res in enumerate(results):
            ob = object_id_binary(task_id, i + 1)
            if res[0] == "v":
                self._resolve_inline(ob, res[1], res[2])
            else:
                # the stored return lives on the executing node: record it
                # in the owner directory before anyone asks
                self._record_owned_location(ob, exec_node)
                self._resolve_plasma(ob)
        # hotpath: end task_result_resolve
        if spec is not None and any(r[0] == "r" for r in results):
            self._record_lineage(spec)
        # Borrower handoff, ordered so an object is always pinned somewhere:
        # 1. register borrows the executor kept (it holds arg refs until we
        #    do — our arg pins keep the containers alive meanwhile);
        # 2. register nested refs inside returns with their owners on our
        #    behalf, then ack the executor so it drops its return pins;
        # 3. only then release our own arg pins.
        exec_addr = p.get("exec_addr")
        if exec_addr is not None:
            for oid, owner in p.get("borrows_kept") or ():
                await self._register_borrow_for(oid, owner, tuple(exec_addr))
            nested_map = p.get("returns_nested") or {}
            if nested_map:
                for i, nested in nested_map.items():
                    roid = object_id_binary(task_id, int(i) + 1)
                    await self._adopt_contains(roid, nested)
                await self._owner_call(
                    exec_addr, "release_return_pins", {"task_id": task_id}
                )
        if spec is not None:
            self._release_task_pins(task_id)
        # Returns whose refs were already dropped can be freed now.
        for i in range(len(results)):
            self._maybe_free(object_id_binary(task_id, i + 1))
        if t0:
            _stage_record("result_return", time.perf_counter() - t0)

    async def _register_borrow_for(self, oid: bytes, owner, borrower: tuple):
        """Register ``borrower`` with ``oid``'s owner (us or remote)."""
        if owner is not None and tuple(owner) == self.addr:
            self._register_borrower(oid, borrower)
        elif owner is not None and tuple(owner) != borrower:
            await self._owner_call(
                owner, "borrow_add", {"object_id": oid, "borrower": borrower}
            )

    async def _adopt_contains(self, container_oid: bytes, nested):
        """We now own ``container_oid`` whose value holds ``nested`` refs:
        pin each (borrow-acquire if foreign) and register with its owner.
        Released when the container is freed (ray: AddNestedObjectIds)."""
        tokens = []
        for oid, owner in nested:
            tokens.append(self.pin_object(oid, owner))
            await self._register_borrow_for(oid, owner, self.addr)
        with self._lock:
            if container_oid in self._owned:
                self._contains.setdefault(container_oid, []).extend(tokens)
                tokens = []
        for t in tokens:  # container already freed: drop immediately
            self.unpin_object(t)

    async def _owner_call(self, owner, method: str, payload, timeout=None):
        try:
            return await self.raylet.request(
                "owner_call",
                {"owner": tuple(owner), "method": method, "payload": payload,
                 "timeout": timeout or cfg.gcs_rpc_timeout_s},
                timeout=(timeout or cfg.gcs_rpc_timeout_s) + 10.0,
            )
        except Exception:
            return {"owner_dead": True}

    def _lineage_insert_locked(self, oid: bytes, spec: TaskSpec):
        """Insert under self._lock, enforcing the FIFO cap."""
        self._lineage[oid] = spec
        overflow = len(self._lineage) - cfg.max_lineage_cache_entries
        if overflow > 0:
            for old in list(self._lineage)[:overflow]:
                del self._lineage[old]

    def _record_lineage(self, spec: TaskSpec):
        """Remember the finalized spec so lost plasma returns can be
        re-executed (ray: task_manager.h lineage pinning, FIFO-capped)."""
        tid = TaskID(spec.task_id)
        with self._lock:
            for i in range(max(1, spec.num_returns)):
                self._lineage_insert_locked(
                    ObjectID.from_index(tid, i + 1).binary(), spec
                )

    async def _handle_task_error(self, spec: Optional[TaskSpec], task_id: bytes, p):
        retriable = p.get("retriable", False)
        app_error = p.get("app_error", False)
        if spec is not None and retriable and spec.attempt < spec.max_retries and (
            not app_error or spec.retry_exceptions
        ):
            spec.attempt += 1
            logger.info("retrying task %s (attempt %d)", spec.name, spec.attempt)
            await asyncio.sleep(cfg.task_retry_delay_ms / 1000.0)
            if p.get("lost_object"):
                # A dependency's plasma copy is gone cluster-wide: try lineage
                # reconstruction before the retry (object_recovery_manager.h).
                # The dependency's owner lives in the matching "r" arg slot.
                lost = p["lost_object"]
                lost_owner = None
                if spec is not None:
                    for a in list(spec.args) + list(spec.kwargs.values()):
                        if a[0] == "r" and a[1] == lost and len(a) > 2:
                            lost_owner = a[2]
                            break
                try:
                    await self._ensure_object_available(lost, lost_owner)
                except Exception as e:
                    logger.warning("dependency recovery failed: %s", e)
            try:
                await self.raylet.request("submit_task", {"spec": spec})
                return
            except Exception:
                pass
        self._submit_stage.pop(task_id, None)
        with self._lock:
            self._specs_inflight.pop(task_id, None)
        tid = TaskID(task_id)
        n_returns = max(1, spec.num_returns) if spec else 1
        if p.get("error_value"):
            meta, data = p["error_value"]
        else:
            if p.get("actor_dead"):
                exc = ActorDiedError(p["error"])
            elif p.get("cancelled"):
                exc = TaskCancelledError(p["error"])
            elif p.get("worker_died"):
                exc = WorkerDiedError(p["error"])
            else:
                exc = RuntimeError(p["error"])
            sv = serialization.serialize_error(exc, spec.name if spec else "")
            meta, data = sv.metadata, sv.to_wire()
        for i in range(n_returns):
            oid = ObjectID.from_index(tid, i + 1)
            self._resolve_inline(oid.binary(), meta, data)
        if spec is not None and spec.num_returns == -1:
            # item futures parked by a dynamic reconstruction must see the
            # terminal error too, or gets on them hang forever
            prefix = spec.task_id
            with self._lock:
                pending = [
                    o for o, f in self._futures.items()
                    if o.startswith(prefix) and not f.done()
                ]
            for o in pending:
                self._resolve_inline(o, meta, data)
        if spec is not None:
            # A failed task may still have stashed arg refs (actor state):
            # register those borrows before dropping our arg pins.
            exec_addr = p.get("exec_addr")
            if exec_addr is not None:
                for oid_b, owner in p.get("borrows_kept") or ():
                    await self._register_borrow_for(oid_b, owner, tuple(exec_addr))
            self._release_task_pins(task_id)

    def _resolve_inline(self, oid: bytes, metadata: bytes, data):
        """``data`` is bytes or a serialization.BufferList (the zero-copy
        wire form — deserialize consumes either)."""
        with self._lock:
            self._memory_store[oid] = (metadata, data)
            fut = self._futures.get(oid)
        if fut and not fut.done():
            fut.set_result(("inline", metadata, data))

    def _resolve_plasma(self, oid: bytes):
        with self._lock:
            fut = self._futures.get(oid)
        if fut and not fut.done():
            fut.set_result(("plasma", None, None))

    # serving borrowers fetching owned values
    async def rpc_fetch_owned(self, conn: Connection, p):
        oid = p["object_id"]
        with self._lock:
            inline = self._memory_store.get(oid)
            fut = self._futures.get(oid)
        if inline is not None:
            return {"inline": inline}
        if fut is not None and fut.done():
            return {"plasma": True}
        if fut is not None:
            return {"pending": True}
        return {"unknown": True}

    async def rpc_dump_stacks(self, conn: Connection, p):
        """Thread stack dump of this process (ray parity:
        dashboard/modules/reporter/profile_manager.py py-spy dump — here
        native sys._current_frames, no external profiler needed)."""
        import sys
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        current = getattr(self, "executor", None)
        task = getattr(current, "current_task_id", None) if current else None
        for ident, frame in sys._current_frames().items():
            stack = "".join(traceback.format_stack(frame))
            out[f"{names.get(ident, '?')}-{ident}"] = stack
        return {
            "pid": os.getpid(),
            "client_id": self.client_id,
            "current_task": task.hex()[:16] if task else None,
            "threads": out,
        }

    # -- on-demand profiling (profiler.py; ray parity: dashboard
    # reporter's py-spy/memray attach, here in-process) ------------------
    def _profiler(self):
        svc = getattr(self, "_profiler_svc", None)
        if svc is None:
            from ray_tpu._private import profiler

            svc = self._profiler_svc = profiler.ProfilerService(
                role="driver" if self.is_driver else "worker"
            )
        return svc

    async def rpc_profile_start(self, conn: Connection, p):
        return self._profiler().start(p or {})

    async def rpc_profile_stop(self, conn: Connection, p):
        return self._annotate_profile(self._profiler().stop(p or {}))

    async def rpc_profile_status(self, conn: Connection, p):
        return self._profiler().status()

    async def rpc_profile_run(self, conn: Connection, p):
        """start -> sleep(duration) -> stop in ONE request: the raylet's
        node fan-out holds no per-worker session state, so a connection
        loss mid-window cannot strand a running profiler (it self-stops
        at the duration)."""
        return self._annotate_profile(await self._profiler().run(p or {}))

    def _annotate_profile(self, out: dict) -> dict:
        out["client_id"] = self.client_id
        out["node_id"] = self.node_id
        ex = getattr(self, "executor", None)
        if ex is not None and getattr(ex, "actor_spec", None) is not None:
            out["actor_id"] = ex.actor_spec.actor_id.hex()
            out["actor_class"] = ex.actor_spec.name
        return out

    # -- metrics plane (metrics_core.py) -------------------------------
    async def rpc_metrics_snapshot(self, conn: Connection, p):
        from ray_tpu._private import metrics_core

        return self._annotate_profile(metrics_core.process_snapshot(
            "driver" if self.is_driver else "worker"))

    # -- step observatory (steptrace.py) -------------------------------
    async def rpc_steptrace_snapshot(self, conn: Connection, p):
        """This process's step-telemetry ring (collective ops, step
        phases, compile events) — the GCS-side merge joins these across
        ranks by (group, seq) into arrival-skew attribution."""
        from ray_tpu._private import steptrace

        return self._annotate_profile(steptrace.process_snapshot())

    # -- request observatory (reqtrace.py) -----------------------------
    async def rpc_reqtrace_snapshot(self, conn: Connection, p):
        """This process's serve request-trace ring (phase spans + stream
        marks) — the GCS-side merge joins these across proxy/replica
        processes by request id into per-request phase breakdowns."""
        from ray_tpu._private import reqtrace

        return self._annotate_profile(reqtrace.process_snapshot())

    # -- memory observatory (memview.py) -------------------------------
    async def rpc_memview_snapshot(self, conn: Connection, p):
        """This process's object-plane view: the owned-object table
        (refcounts, pins, inlined sizes, creation callsites) plus the
        union of every oid it references — what the GCS-side merge joins
        against store ledgers for leak attribution — and the flow ring."""
        return self._annotate_profile(
            memview.process_snapshot(extra=self._memview_tables()))

    def _memview_tables(self) -> dict:
        with self._lock:
            owned = list(self._owned)[:10_000]
            refs = dict(self._local_refs)
            pins = dict(self._escape_pins)
            # inline values are bytes OR the zero-copy wire forms
            # (BufferList / memoryview) — len() is wrong or absent for
            # those; one such entry must not poison the whole snapshot
            inlined = {oid: (v[1].nbytes if hasattr(v[1], "nbytes")
                             else len(v[1]))
                       for oid, v in self._memory_store.items()}
            borrows = [oid for oid, st in self._borrow_state.items()
                       if st.get("count", 0) > 0]
            contains = list(self._contains)
        now = time.time()
        rows = []
        for oid in owned:
            info = memview.put_info(oid)
            row = {
                "object_id": oid.hex(),
                "refs": refs.get(oid, 0),
                "pins": pins.get(oid, 0),
                "inlined": oid in inlined,
            }
            if oid in inlined:
                row["size"] = inlined[oid]
            if info is not None:
                site, ts, nbytes, kind = info
                row["callsite"] = site
                row["age_s"] = round(now - ts, 3)
                row.setdefault("size", nbytes)
                row["kind"] = kind
            rows.append(row)
        referenced = {oid.hex() for oid in refs}
        referenced.update(oid.hex() for oid in borrows)
        referenced.update(oid.hex() for oid in pins)
        referenced.update(oid.hex() for oid in contains)
        referenced.update(oid.hex() for oid in owned)
        # bytes held outside the ObjectRef world (arena KV pages etc.):
        # the holder must appear referenced or live pages read as leaks
        referenced.update(o.hex() for o in memview.external_pins())
        return {"owned": rows, "referenced": sorted(referenced)}

    async def rpc_pubsub(self, conn: Connection, p):
        self._dispatch_pubsub(p["channel"], p["message"])

    async def rpc_pubsub_batch(self, conn: Connection, p):
        # batched delivery (GCS coalesces same-tick publishes per peer)
        for channel, message in p["batch"]:
            self._dispatch_pubsub(channel, message)

    def _dispatch_pubsub(self, channel, message):
        for cb in self._pubsub_handlers.get(channel, ()):
            try:
                cb(message)
            except Exception:
                logger.exception("pubsub callback failed")

    # delegated to the executor on worker processes
    async def _await_executor(self):
        while self.executor is None:
            await asyncio.sleep(0.005)
        return self.executor

    async def rpc_execute_task(self, conn: Connection, p):
        ex = await self._await_executor()
        return await self._execute_one(ex, p["spec"],
                                       direct=conn is not self.raylet)

    async def rpc_execute_task_batch(self, conn: Connection, p):
        """Batched direct push: N specs in ONE request frame, N result
        dicts in ONE response (ray parity: the reference batches its task
        plane at every layer — src/ray/rpc/, task_event_buffer.h:199).
        Specs run SEQUENTIALLY in arrival order: plain tasks serialize on
        the single-thread pool anyway, and skipping the per-task dispatch
        asyncio.Task + request/response frame pair is precisely the
        per-message event-loop cost this path exists to amortize."""
        ex = await self._await_executor()
        direct = conn is not self.raylet
        specs = p["specs"]
        if direct:
            # one provisional log offset for the whole batch (items run
            # sequentially; each FINISHED event carries its exact range)
            from ray_tpu._private import logplane

            open_fields = logplane.open_event_fields()
            for spec in specs:
                self._emit_direct_task_event(spec, "RUNNING", **open_fields)

        buf: list = []
        flush_ref: list = [None]

        async def flush_results():
            # one tick: results completing in the same loop burst share a
            # task_result_batch frame; a lone (slow) result still flushes
            # on the next tick — no added latency
            await asyncio.sleep(0)
            while buf:
                chunk, buf[:] = list(buf), []
                if len(chunk) == 1:
                    await conn.notify("task_result", chunk[0])
                else:
                    await conn.notify("task_result_batch", chunk)

        async def deliver(spec: TaskSpec, result: dict):
            # Stream each result back the moment it lands (same payload
            # shape _direct_result builds on the owner) — the batch
            # RESPONSE is only a completion ack, so ray.wait sees early
            # tasks while the batch tail still runs.
            if direct:
                extra = _log_span_fields(result)
                if result.get("error") is not None:
                    self._emit_direct_task_event(
                        spec, "FAILED",
                        error=str(result.get("error"))[:200], **extra,
                    )
                else:
                    self._emit_direct_task_event(
                        spec, "FINISHED", duration=result.get("duration"),
                        **extra,
                    )
                if result.get("stored_objects"):
                    try:
                        await self.raylet.notify(
                            "register_stored",
                            {"object_ids": list(result["stored_objects"])},
                        )
                    except Exception:
                        pass
            buf.append({
                "task_id": spec.task_id,
                "results": result.get("results"),
                "error": result.get("error"),
                "error_value": result.get("error_value"),
                "app_error": result.get("app_error", False),
                "retriable": result.get("retriable", False),
                "attempt": spec.attempt,
                "exec_addr": result.get("exec_addr"),
                "borrows_kept": result.get("borrows_kept"),
                "returns_nested": result.get("returns_nested"),
                "dynamic_return_oids": result.get("dynamic_return_oids"),
            })
            t = flush_ref[0]
            if t is None or t.done():
                flush_ref[0] = self._spawn(flush_results())

        await ex.execute_task_batch(specs, deliver)
        t = flush_ref[0]
        if t is not None:
            # every result must be on the wire BEFORE the ack: the owner
            # treats acked batches as fully resulted on conn failure
            await asyncio.shield(t)
        return {"done": len(specs)}

    async def _execute_one(self, ex, spec: TaskSpec, direct: bool):
        if direct:
            # the raylet never sees direct-push tasks, so this worker owns
            # their observability record (state API / timeline parity with
            # raylet-routed tasks); log offsets ride along so the raylet's
            # tailer can attribute streamed lines by byte range
            from ray_tpu._private import logplane

            self._emit_direct_task_event(spec, "RUNNING",
                                         **logplane.open_event_fields())
        result = await ex.execute_task(spec)
        if direct:
            extra = _log_span_fields(result)
            if result.get("error") is not None:
                self._emit_direct_task_event(
                    spec, "FAILED",
                    error=str(result.get("error"))[:200], **extra,
                )
            else:
                self._emit_direct_task_event(
                    spec, "FINISHED", duration=result.get("duration"),
                    **extra,
                )
            if result.get("stored_objects"):
                # stored outputs must be self-reported for location tracking
                try:
                    await self.raylet.notify(
                        "register_stored",
                        {"object_ids": list(result["stored_objects"])},
                    )
                except Exception:
                    pass
        return result

    def _emit_direct_task_event(self, spec: TaskSpec, state: str, **extra):
        ev = {
            "task_id": spec.task_id.hex(),
            "name": spec.name,
            "job_id": spec.job_id.hex() if spec.job_id else None,
            "actor_id": spec.actor_id.hex() if spec.actor_id else None,
            "attempt": spec.attempt,
            "state": state,
            "ts": time.time(),
            "node_id": self.node_id,
            "pid": os.getpid(),
        }
        ev.update(extra)
        self._tev_buf.append(ev)
        if not self._tev_flushing:
            self._tev_flushing = True
            self._spawn(self._flush_task_events())

    async def _flush_task_events(self):
        # debounced: a sync call loop emits RUNNING + FINISHED per call on
        # separate ticks — flush-per-tick ships ~2 notify frames per call
        # to the raylet. Buffering for the window coalesces a whole run of
        # calls into one frame; the raylet batches onward to the GCS on
        # its own timer, and exit paths (rpc_exit /
        # flush_task_events_sync) still drain immediately.
        dt = cfg.task_events_flush_interval_s
        await asyncio.sleep(dt if dt > 0 else 0)
        buf, self._tev_buf = self._tev_buf, []
        self._tev_flushing = False
        if not buf:
            return
        try:
            await self.raylet.notify("task_events", {"events": buf})
        except Exception:
            pass

    def flush_task_events_sync(self, timeout: float = 2.0):
        """Push any buffered task events to the raylet NOW, from any
        thread. Exit paths call this (worker_main's SIGTERM/atexit hooks)
        so a dying worker's last events — the most interesting ones in a
        chaos lane — are not lost with the process."""
        if not self._tev_buf:
            return
        buf, self._tev_buf = self._tev_buf, []
        try:
            fut = asyncio.run_coroutine_threadsafe(
                self.raylet.notify("task_events", {"events": buf}),
                self.io.loop,
            )
            fut.result(timeout=timeout)
        except Exception:
            pass

    async def rpc_become_actor(self, conn: Connection, p):
        ex = await self._await_executor()
        return await ex.become_actor(p["spec"])

    async def rpc_exit(self, conn: Connection, p):
        # drain observability buffers before dying: buffered task events
        # go to the raylet (we are ON the io loop — notify directly), and
        # stdio flushes so the log tailer's final drain sees everything
        buf, self._tev_buf = self._tev_buf, []
        if buf:
            try:
                await self.raylet.notify("task_events", {"events": buf})
            except Exception:
                pass
        try:
            import sys

            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        logging.shutdown()
        os._exit(0)

    def on_disconnect(self, conn: Connection):
        """Client-side connection loss. A dropped GCS conn means the GCS died
        or restarted: reconnect + re-register + resubscribe (reference
        analog: the auto-reconnect GcsClient decorator, _raylet.pyx:2124 +
        pubsub resubscribe on RayletNotifyGCSRestart)."""
        if conn is self.gcs and getattr(self, "connected", False):
            return self._gcs_reconnect_loop()
        return None

    async def _gcs_reconnect_loop(self):
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # interpreter teardown: the io loop is already gone
        deadline = loop.time() + cfg.gcs_client_reconnect_timeout_s
        delay = 0.2
        while getattr(self, "connected", False):
            if loop.time() > deadline:
                logger.error("GCS unreachable for %.0fs; giving up",
                             cfg.gcs_client_reconnect_timeout_s)
                return
            try:
                # short inner dial; the outer loop paces the long outage
                conn = await connect(self.gcs_addr[0], self.gcs_addr[1],
                                     handler=self, name="gcs-conn",
                                     retries=3)
                await conn.request(
                    "register_client",
                    {"client_id": self.client_id, "job_id": self.job_id,
                     "is_driver": self.is_driver},
                )
                for channel in self._pubsub_handlers:
                    await conn.request("subscribe", {"channel": channel})
                self.gcs = conn
                logger.info("reconnected to GCS at %s:%s", *self.gcs_addr)
                return
            except Exception:
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 2.0)

    def subscribe(self, channel: str, callback):
        self._pubsub_handlers.setdefault(channel, []).append(callback)
        self.io.run(self.gcs.request("subscribe", {"channel": channel}))

    def publish(self, channel: str, message):
        self.io.run(self.gcs.request("publish", {"channel": channel, "message": message}))

    # ------------------------------------------------------------------
    # objects: slab-arena write path (slab_arena.py)
    # ------------------------------------------------------------------
    def store_put(self, oid: ObjectID, sv: serialization.SerializedValue,
                  callsite: Optional[str] = None):
        """Store a serialized value (> inline threshold) into the node
        object plane. Slab arena when this client holds or can lease a
        write slab: bump-allocate + seal + shared-index publish, with
        accounting batched to the raylet (no per-put RPC). One-file
        fallback otherwise — and on the io-loop thread when the slab is
        full (a refill RPC must never block the loop that sends it);
        the refill then runs in the background for the next put.
        ``callsite`` (the creating user line) rides the slab report into
        the store-side ledger so leak verdicts survive this owner's
        death."""
        t0 = time.perf_counter()
        if self._arena_put(oid, sv, callsite):
            mx = object_store._mx()
            mx.put_lat.record(time.perf_counter() - t0)
            mx.put_bytes.record(sv.total_data_len)
            mx.slab_puts.inc()
            return
        object_store.write_object(
            self.store_dir, oid, sv.metadata, sv.buffers, sv.total_data_len
        )
        self._register_put_fallback(oid)

    def _slab_try_put(self, oid: ObjectID,
                      sv: serialization.SerializedValue,
                      callsite: Optional[str] = None) -> bool:
        ent = self._slab_writer.try_put(
            oid.binary(), sv.metadata, sv.buffers, sv.total_data_len
        )
        if ent is None:
            return False
        if callsite:
            ent["c"] = callsite
        self._queue_slab_report(ent)
        return True

    def _arena_put(self, oid: ObjectID,
                   sv: serialization.SerializedValue,
                   callsite: Optional[str] = None) -> bool:
        if self._slab_writer is None:
            return False
        if self._slab_try_put(oid, sv, callsite):
            return True
        need = slab_arena.entry_size(len(sv.metadata), sv.total_data_len)
        if threading.current_thread() is self.io.thread:
            self._kick_slab_refill(need)
            return False
        with self._slab_lease_lock:
            if self._slab_try_put(oid, sv, callsite):
                return True  # a racing refill already won
            try:
                ok = self.io.run(self._slab_refill(need),
                                 timeout=cfg.gcs_rpc_timeout_s * 2)
            except Exception:
                ok = False
            return bool(ok) and self._slab_try_put(oid, sv, callsite)

    async def _slab_refill(self, entry_total: int) -> bool:
        """Serialized refill: at most ONE lease request in flight per
        client — a second caller (e.g. an io-thread result put racing a
        user-thread driver put) joins the in-flight refill instead of
        double-leasing; the loser's attach would otherwise silently
        detach a just-granted segment with no seal, stranding it leased
        (and charged) until disconnect."""
        t = self._slab_refill_task
        if t is None or t.done():
            t = asyncio.get_running_loop().create_task(
                self._do_slab_refill(entry_total)
            )
            self._slab_refill_task = t
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)
        try:
            return bool(await asyncio.shield(t))
        except Exception:
            return False

    async def _do_slab_refill(self, entry_total: int) -> bool:
        """Retire the full slab and lease a fresh one (the one lease RPC
        amortized over every put that lands in it)."""
        w = self._slab_writer
        size = w.lease_size_for(entry_total, cfg.slab_size_bytes,
                                cfg.slab_min_lease_bytes)
        seal = w.take_seal()
        seals = ([seal] if seal else []) + self._pending_seals
        try:
            r = await self.raylet.request(
                "lease_slab", {"bytes": size, "seals": seals}
            )
        except Exception:
            # transport failure: the raylet never saw these seals — carry
            # them ALL into the next attempt so the segments get retired
            # (worst case, disconnect reclaim retires them). Never drop
            # any: a dropped seal leaves its segment leased and fully
            # charged (exempt from eviction) until client disconnect,
            # and the list grows by at most one tiny dict per failed
            # refill, so it stays bounded by refill cadence
            self._pending_seals = seals
            return False
        self._pending_seals = []
        if not r.get("ok"):
            return False
        w.attach(r["seg_id"], r["size"])
        return True

    def _kick_slab_refill(self, entry_total: int):
        t = self._slab_refill_task
        if t is not None and not t.done():
            return
        task = asyncio.get_running_loop().create_task(
            self._do_slab_refill(entry_total)
        )
        self._slab_refill_task = task
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _queue_slab_report(self, ent: dict):
        """Batched accounting: sealed entries ride one slab_report notify
        per io-loop burst instead of one registration RPC per put."""
        with self._lock:
            self._slab_reports.append(ent)
            if self._slab_flushing:
                return
            self._slab_flushing = True
        try:
            self.io.call_soon(self._flush_slab_reports())
        except RuntimeError:  # loop stopped (shutdown): reconcile recovers
            with self._lock:
                self._slab_flushing = False

    async def _flush_slab_reports(self):
        while True:
            await asyncio.sleep(0)  # coalesce the current put burst
            with self._lock:
                batch, self._slab_reports = self._slab_reports, []
                if not batch:
                    self._slab_flushing = False
                    return
            try:
                await self.raylet.notify("slab_report", {"objects": batch})
            except Exception:
                # transient raylet unreachability must not strand the
                # batch (the seal/death reconcile would cover it only at
                # the NEXT refill or disconnect — an idle writer's
                # objects would stay invisible to the directory):
                # requeue bounded and let the next put retrigger a flush
                with self._lock:
                    self._slab_reports = (batch + self._slab_reports)[:10_000]
                    self._slab_flushing = False
                return

    def _register_put_fallback(self, oid: ObjectID):
        """Legacy one-file accounting (register_external + location)."""
        payload = {"object_id": oid.binary()}
        if threading.current_thread() is self.io.thread:
            async def _reg():
                # retried: an unregistered fallback .obj is invisible to
                # the raylet's accounting/eviction — a dropped frame here
                # would leak the file until session teardown
                for delay in (0.0, 0.5, 2.0):
                    if delay:
                        await asyncio.sleep(delay)
                    try:
                        await self.raylet.request("register_put", payload)
                        return
                    except Exception:
                        continue
            t = asyncio.get_running_loop().create_task(_reg())
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)
        else:
            self.io.run(self.raylet.request("register_put", payload))

    # ------------------------------------------------------------------
    # objects: put/get/wait
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        sv = serialization.serialize(value)
        return self._put_serialized(sv)

    def _put_serialized(self, sv: serialization.SerializedValue) -> ObjectRef:
        with self._lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(self.task_id, idx)
        # memory observatory: stamp the creating user callsite so a
        # leaked put groups by the line that made it (flag-gated; a
        # bounded frame walk, ~1µs against a >=100µs store put). The
        # tag is computed ONCE and also handed to store_put below, which
        # persists it into the store-side ledger — a dead owner's leak
        # verdict then still names the line that made the object
        callsite = memview.callsite_tag() if memview.is_enabled() else None
        memview.record_put(
            oid.binary(), sv.total_data_len,
            "inline" if sv.total_data_len
            <= cfg.max_direct_call_object_size else "put",
            callsite=callsite)
        # Refs nested in the stored value are kept alive by this container
        # until it is freed (ray: reference_count.h AddNestedObjectIds). The
        # nested refs are live python ObjectRefs here, so their borrows are
        # already registered with their owners; the pin extends the lifecycle.
        tokens = [self.pin_object(o, w) for o, w in sv.nested_refs]
        if sv.total_data_len <= cfg.max_direct_call_object_size:
            # to_bytes, not to_wire: put() snapshots — the stored value must
            # not alias the caller's (possibly mutated-later) buffers
            with self._lock:
                self._memory_store[oid.binary()] = (sv.metadata, sv.to_bytes())
                self._owned.add(oid.binary())
                if tokens:
                    self._contains[oid.binary()] = tokens
        else:
            # slab-arena write: bump+seal+index, accounting batched — no
            # blocking per-put registration round trip
            self.store_put(oid, sv, callsite=callsite)
            self._record_owned_location(oid.binary(), self.node_id)
            with self._lock:
                self._owned.add(oid.binary())
                if tokens:
                    self._contains[oid.binary()] = tokens
        ref = ObjectRef(oid, self.addr)
        self.add_local_ref(ref)
        return ref

    def future_for(self, ref: ObjectRef) -> concurrent.futures.Future:
        with self._lock:
            fut = self._futures.get(ref.binary())
            if fut is not None:
                return fut
            if ref.binary() in self._memory_store:
                fut = concurrent.futures.Future()
                fut.set_result(("inline",) + self._memory_store[ref.binary()])
                self._futures[ref.binary()] = fut
                return fut
            fut = concurrent.futures.Future()
            self._futures[ref.binary()] = fut
        if object_store.object_exists(self.store_dir, ref.id()):
            if not fut.done():
                fut.set_result(("plasma", None, None))
            return fut
        if ref.binary() in self._owned or (
            ref.owner is not None and tuple(ref.owner) == self.addr
        ):
            # Owned but not local (e.g. a dynamic return stored on the
            # executing node, or a lost copy): pull, else reconstruct.
            self.io.call_soon(self._resolve_owned_missing(ref, fut))
            return fut
        # Borrowed ref: resolve in background (plasma pull or owner fetch).
        self.io.call_soon(self._resolve_borrowed(ref, fut))
        return fut

    async def _resolve_owned_missing(self, ref: ObjectRef,
                                     fut: concurrent.futures.Future):
        oid = ref.binary()
        try:
            ok = await self.raylet.request(
                "pull_object",
                {"object_id": oid, "timeout": cfg.object_pull_timeout_s,
                 "owner": self.addr},
            )
            if ok.get("ok") and object_store.object_exists(
                self.store_dir, ref.id()
            ):
                if not fut.done():
                    fut.set_result(("plasma", None, None))
                return
        except Exception:
            pass
        try:
            rfut = await self._reconstruct_owned(oid)
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
            return
        if rfut is fut:
            return  # resolution arrives via the task-result path

        def _copy(rf):
            if fut.done():
                return
            try:
                fut.set_result(rf.result())
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        rfut.add_done_callback(_copy)

    async def _resolve_borrowed(self, ref: ObjectRef, fut: concurrent.futures.Future):
        oid = ref.binary()
        deadline = time.monotonic() + cfg.object_pull_timeout_s
        while time.monotonic() < deadline and not fut.done():
            if object_store.object_exists(self.store_dir, ref.id()):
                if not fut.done():
                    fut.set_result(("plasma", None, None))
                return
            owner = ref.owner
            if owner is not None and tuple(owner) != self.addr:
                try:
                    r = await self.raylet.request(
                        "fetch_owned_routed", {"owner": tuple(owner), "object_id": oid},
                        timeout=10.0,
                    )
                except Exception:
                    r = {}
                if r.get("inline"):
                    meta, data = r["inline"]
                    self._resolve_inline(oid, meta, data)
                    return
                if r.get("plasma"):
                    ok = (await self.raylet.request(
                        "pull_object",
                        {"object_id": oid, "owner": tuple(owner)}))["ok"]
                    if ok and not fut.done():
                        fut.set_result(("plasma", None, None))
                        return
                if r.get("pending"):
                    # Producer still running: keep waiting past the deadline.
                    deadline = time.monotonic() + cfg.object_pull_timeout_s
            else:
                try:
                    ok = (await self.raylet.request(
                        "pull_object",
                        {"object_id": oid,
                         "owner": tuple(owner) if owner else None}))["ok"]
                    if ok and not fut.done():
                        fut.set_result(("plasma", None, None))
                        return
                except Exception:
                    pass
            await asyncio.sleep(0.05)
        if not fut.done():
            fut.set_exception(GetTimeoutError(f"could not resolve {ref}"))

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        futs = [self.future_for(r) for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        values = []
        for r, f in zip(refs, futs):
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                if remaining is None and cfg.get_stall_dump_s > 0:
                    kind, meta, data = self._wait_with_stall_dump(r, f)
                else:
                    kind, meta, data = f.result(remaining)
            except concurrent.futures.TimeoutError:
                raise GetTimeoutError(
                    f"Get timed out: {r} not ready after {timeout}s"
                ) from None
            values.append(self._materialize(r, kind, meta, data))
        return values[0] if single else values

    def _wait_with_stall_dump(self, ref: ObjectRef, f):
        """Untimed get(): wait in stall-sized slices so a result that never
        arrives produces a transport-state diagnostic instead of a silent
        hang (the WARNING is the user-visible symptom; the dump file is for
        postmortems)."""
        stalls = 0
        while True:
            try:
                return f.result(cfg.get_stall_dump_s)
            except concurrent.futures.TimeoutError:
                stalls += 1
                dump = self.debug_transport_state()
                msg = (f"get() blocked {stalls * cfg.get_stall_dump_s:.0f}s "
                       f"on {ref}; transport state: {dump}")
                logger.warning(msg)
                path = os.environ.get("RAY_TPU_STALL_DUMP_FILE")
                if path:
                    try:
                        with open(path, "a") as fh:
                            fh.write(msg + "\n")
                            if stalls == 3:
                                # one-shot deep dump: the io loop's pending
                                # task stacks localize a wedged coroutine
                                # that the transport counters can't
                                import io as _io

                                buf = _io.StringIO()
                                try:
                                    from ray_tpu._private.profiling import \
                                        all_asyncio_tasks

                                    for t in all_asyncio_tasks():
                                        if not t.done():
                                            buf.write(f"--- {t!r} ---\n")
                                            t.print_stack(file=buf)
                                except Exception as de:
                                    buf.write(f"(dump failed: {de!r})\n")
                                fh.write(buf.getvalue())
                    except OSError:
                        pass

    def debug_transport_state(self) -> dict:
        """Snapshot of the direct-push machinery, readable without the io
        loop (diagnosis only). Every container is list()-snapshotted before
        iteration and the whole read is exception-guarded: the io thread
        mutates these dicts concurrently, and a diagnostic must never turn
        a healthy (if slow) get() into a RuntimeError."""
        try:
            state: dict = {
                "direct_q": {
                    repr(k): len(q) for k, q in list(self._direct_q.items())
                },
                "pumps": {
                    repr(k): ("done" if t.done() else "live")
                    for k, t in list(self._direct_pumps.items())
                },
                "bg_tasks": len(self._bg_tasks),
                "events_set": {
                    repr(k): ev.is_set()
                    for k, ev in list(self._direct_events.items())
                },
                "direct_conns": {
                    f"{h}:{p}": {
                        "closed": c.closed, "pending": len(c._pending),
                    }
                    for (h, p), c in list(self._direct_conns.items())
                },
                "raylet_pending": len(self.raylet._pending)
                if self.raylet is not None else None,
                "specs_inflight": {
                    tid.hex()[:8]: (s.name, self._submit_stage.get(tid, "?"))
                    for tid, s in list(self._specs_inflight.items())[:16]
                },
                "actor_direct": {
                    aid.hex()[:8]: {
                        "q": len(st["q"]), "running": st["running"],
                        "inflight": st.get("inflight"),
                        "fallback": st.get("fallback", False),
                    }
                    for aid, st in list(self._actor_direct.items())
                },
            }
        except Exception as e:  # torn read mid-mutation: partial is fine
            state = {"error": f"snapshot failed: {e!r}"}
        return state

    def _materialize(self, ref: ObjectRef, kind, meta, data):
        if kind == "inline":
            with _deser_container(ref.binary()):
                return serialization.deserialize(meta, data)
        oid = ref.id()
        buf = object_store.read_object(self.store_dir, oid)
        if buf is None:
            ok = self.io.run(self.raylet.request(
                "pull_object",
                {"object_id": ref.binary(), "owner": ref.owner}))
            if ok.get("ok"):
                buf = object_store.read_object(self.store_dir, oid)
        if buf is None:
            # Plasma copy gone cluster-wide (or the local file was deleted
            # behind a stale store record): invalidate, re-pull, and fall
            # back to lineage reconstruction (object_recovery_manager.h:44).
            buf, inline = self._recover_object(ref)
            if buf is None:
                with _deser_container(ref.binary()):
                    return serialization.deserialize(*inline)
        with self._lock:
            self._pinned_buffers.pop(ref.binary(), None)
            self._pinned_buffers[ref.binary()] = buf
        with _deser_container(ref.binary()):
            return serialization.deserialize(buf.metadata, buf.data)

    def _recover_object(self, ref: ObjectRef):
        """Returns (buffer, None) or (None, (meta, data)) for a value that
        came back inline (e.g. the reconstructed task errored)."""
        oid = ref.id()
        try:
            self.io.run(self.raylet.request(
                "report_lost_object", {"object_id": ref.binary()}))
            # Short probe: if no other node holds a copy, fail fast into
            # reconstruction instead of waiting out the full pull timeout.
            ok = self.io.run(self.raylet.request(
                "pull_object", {"object_id": ref.binary(), "timeout": 2.0}))
            if ok.get("ok"):
                buf = object_store.read_object(self.store_dir, oid)
                if buf is not None:
                    return buf, None
        except Exception:
            pass
        owner = ref.owner
        if owner is not None and tuple(owner) != self.addr:
            # Borrowed: ask the owner to reconstruct, then pull again.
            r = self.io.run(self._owner_call(
                owner, "reconstruct_object", {"object_id": ref.binary()},
                timeout=cfg.object_pull_timeout_s * 2,
            ))
            if r.get("ok"):
                ok = self.io.run(self.raylet.request(
                    "pull_object", {"object_id": ref.binary()}))
                if ok.get("ok"):
                    buf = object_store.read_object(self.store_dir, oid)
                    if buf is not None:
                        return buf, None
            raise GetTimeoutError(f"object {ref} lost; owner could not recover it")
        fut = self.io.run(self._reconstruct_owned(ref.binary()))
        kind, meta, data = fut.result(cfg.object_pull_timeout_s * 2)
        if kind == "inline":
            return None, (meta, data)
        buf = object_store.read_object(self.store_dir, oid)
        if buf is None:
            ok = self.io.run(self.raylet.request(
                "pull_object", {"object_id": ref.binary()}))
            if ok.get("ok"):
                buf = object_store.read_object(self.store_dir, oid)
        if buf is None:
            raise GetTimeoutError(f"object {ref} unavailable after reconstruction")
        return buf, None

    def wait(self, refs: List[ObjectRef], num_returns=1, timeout=None,
             fetch_local=True):
        if not fetch_local:
            return self._wait_no_fetch(refs, num_returns, timeout)
        futs = {self.future_for(r): r for r in refs}
        deadline = None if timeout is None else time.monotonic() + timeout
        done: set = set()
        while len(done) < num_returns:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining < 0:
                break
            d, _ = concurrent.futures.wait(
                [f for f in futs if f not in done], timeout=remaining,
                return_when=concurrent.futures.FIRST_COMPLETED,
            )
            if not d:
                break
            done |= d
        ready_set = {futs[f] for f in done}
        ordered_ready = [r for r in refs if r in ready_set][:num_returns]
        picked = set(ordered_ready)
        not_ready = [r for r in refs if r not in picked]
        return ordered_ready, not_ready

    def _wait_no_fetch(self, refs, num_returns, timeout):
        """wait(fetch_local=False): readiness without pulling the values to
        this node (ray: wait's fetch_local contract — the reference only
        checks object availability, it does not start a transfer)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: set = set()
        while True:
            for r in refs:
                if r in ready:
                    continue
                if self._is_available_somewhere(r):
                    ready.add(r)
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() > deadline:
                break
            time.sleep(cfg.wait_poll_interval_s)
        ordered_ready = [r for r in refs if r in ready][:num_returns]
        picked = set(ordered_ready)
        return ordered_ready, [r for r in refs if r not in picked]

    def _is_available_somewhere(self, ref: ObjectRef) -> bool:
        oid = ref.binary()
        with self._lock:
            if oid in self._memory_store:
                return True
            fut = self._futures.get(oid)
        if fut is not None and fut.done() and fut.exception() is None:
            return True
        if object_store.object_exists(self.store_dir, ref.id()):
            return True
        owner = ref.owner
        if owner is not None and tuple(owner) != self.addr:
            try:
                r = self.io.run(self.raylet.request(
                    "fetch_owned_routed",
                    {"owner": tuple(owner), "object_id": oid}, timeout=5.0,
                ))
            except Exception:
                return False
            return bool(r.get("inline") or r.get("plasma"))
        return False

    # ------------------------------------------------------------------
    # reference counting + borrower protocol (ray: reference_count.h:61)
    #
    # Owner side: an owned object stays alive while it has local python
    # refs, escape pins (serialized copies in flight), or registered remote
    # borrowers. Each registered borrower is long-polled (wait_ref_removed);
    # its reply arrives when the borrower's last reference drops and carries
    # any refs it borrowed *through* the object for handoff.
    #
    # Borrower side: one state per oid counting python refs + serialize-out
    # holds + containment holds; when it hits zero, pending owner polls
    # resolve. Every registration handoff is acknowledged before the pin
    # protecting the object during the handoff is released, so the object is
    # pinned somewhere at every instant.
    # ------------------------------------------------------------------
    def add_local_ref(self, ref: ObjectRef):
        with self._lock:
            self._local_refs[ref.binary()] = self._local_refs.get(ref.binary(), 0) + 1
        ref._counted = True  # __del__ releases this count

    def defer_ref_release(self, ref_binary: bytes):
        """Called from ObjectRef.__del__ (any thread, any GC point):
        deque.append is atomic and lock-free, so this is safe even when the
        interpreter is mid-way through a locked core-worker section. The
        release-drain thread applies the actual decrement."""
        self._deferred_releases.append(ref_binary)
        self._release_event.set()

    def _release_drain_loop(self):
        while getattr(self, "connected", True):
            try:
                oid = self._deferred_releases.popleft()
            except IndexError:
                self._release_event.clear()
                if self._deferred_releases:  # raced a producer's append
                    continue
                self._release_event.wait(timeout=cfg.deferred_release_wait_s)
                continue
            try:
                self.remove_local_ref(oid)
            except Exception:
                logger.exception("deferred ref release failed")

    def remove_local_ref(self, ref_binary: bytes):
        with self._lock:
            if ref_binary in self._borrow_state and ref_binary not in self._owned:
                borrowed = True
            else:
                borrowed = False
                n = self._local_refs.get(ref_binary, 0) - 1
                if n <= 0:
                    self._local_refs.pop(ref_binary, None)
                else:
                    self._local_refs[ref_binary] = n
                    return
        if borrowed:
            self._borrow_release(ref_binary)
        else:
            self._maybe_free(ref_binary)

    def register_borrowed_ref(self, ref: ObjectRef):
        """Called for every deserialized ObjectRef. Owned refs round-tripping
        home count as local refs; foreign refs start/extend a borrow."""
        oid = ref.binary()
        with self._lock:
            if oid in self._owned:
                self._local_refs[oid] = self._local_refs.get(oid, 0) + 1
                ref._counted = True
                return
            st = self._borrow_state.get(oid)
            if st is None:
                st = {"count": 0, "owner": None, "waiters": []}
                self._borrow_state[oid] = st
            st["count"] += 1
            if st["owner"] is None and ref.owner is not None:
                st["owner"] = tuple(ref.owner)
            ref._counted = True
            # Provenance tracking matters only when the container itself is a
            # borrowed object with live state (its owner will poll us and the
            # reply hands these children off). Owned containers pin children
            # via _contains, and executor args report children directly in
            # borrows_kept — recording those here would leak entries forever.
            container = getattr(_DESER_CTX, "container", None)
            if (container is not None and container != oid
                    and container in self._borrow_state):
                self._borrowed_via.setdefault(container, set()).add(oid)

    def pin_object(self, oid: bytes, owner) -> tuple:
        """Take one keep-alive pin: escape pin if owned, borrow hold if not.
        Returns a token for unpin_object."""
        with self._lock:
            if oid in self._owned:
                self._escape_pins[oid] = self._escape_pins.get(oid, 0) + 1
                return ("o", oid)
            st = self._borrow_state.get(oid)
            if st is None:
                st = {"count": 0, "owner": None, "waiters": []}
                self._borrow_state[oid] = st
            st["count"] += 1
            if st["owner"] is None and owner is not None:
                st["owner"] = tuple(owner)
            return ("b", oid)

    def unpin_object(self, token: tuple):
        kind, oid = token
        if kind == "o":
            with self._lock:
                n = self._escape_pins.get(oid, 0) - 1
                if n <= 0:
                    self._escape_pins.pop(oid, None)
                else:
                    self._escape_pins[oid] = n
                    return
            self._maybe_free(oid)
        else:
            self._borrow_release(oid)

    def _borrow_release(self, oid: bytes):
        with self._lock:
            st = self._borrow_state.get(oid)
            if st is None:
                return
            st["count"] -= 1
            if st["count"] > 0:
                return
            self._borrow_state.pop(oid, None)
            waiters = st["waiters"]
            # Children first borrowed while deserializing this object that
            # are still live: hand them off to the container's owner.
            inherited = []
            for child in self._borrowed_via.pop(oid, ()):
                cst = self._borrow_state.get(child)
                if cst is not None and cst.get("owner"):
                    inherited.append((child, cst["owner"]))
        if waiters:
            def _resolve():
                for f in waiters:
                    if not f.done():
                        f.set_result(inherited)
            self.io.loop.call_soon_threadsafe(_resolve)

    def borrowed_refs_held(self):
        """Live borrows of this process: [(oid, owner)] — reported to task
        owners at completion (ray: PushTaskReply.borrowed_refs)."""
        with self._lock:
            return [
                (oid, st["owner"])
                for oid, st in self._borrow_state.items()
                if st["count"] > 0 and st.get("owner")
            ]

    # -- owner-side borrower registry ----------------------------------
    def _register_borrower(self, oid: bytes, borrower: tuple):
        if tuple(borrower) == self.addr:
            return
        with self._lock:
            if oid not in self._owned:
                return
            s = self._borrowers.setdefault(oid, set())
            if tuple(borrower) in s:
                return
            s.add(tuple(borrower))
        self.io.call_soon(self._poll_borrower(oid, tuple(borrower)))

    async def _poll_borrower(self, oid: bytes, borrower: tuple):
        """Long-poll one borrower until it drops the ref (WaitForRefRemoved).
        A dead borrower is pruned after a few failures."""
        failures = 0
        while True:
            with self._lock:
                if oid not in self._owned or borrower not in self._borrowers.get(oid, ()):
                    return
            r = await self._owner_call(
                borrower, "wait_ref_removed", {"object_id": oid},
                timeout=cfg.borrower_poll_timeout_s,
            )
            if r.get("timeout"):
                failures = 0
                continue
            if r.get("removed"):
                for child, child_owner in r.get("inherited", ()):
                    await self._register_borrow_for(child, child_owner, borrower)
                break
            failures += 1
            if failures >= cfg.borrower_poll_retries:
                logger.warning(
                    "borrower %s of %s unreachable; dropping its borrow",
                    borrower, oid.hex()[:16],
                )
                break
            # Exponential backoff: a brief raylet/peer outage must not free
            # an object a live borrower still uses (transient errors and a
            # dead borrower look the same through the routing layer).
            await asyncio.sleep(min(30.0, 2.0 ** failures))
        with self._lock:
            s = self._borrowers.get(oid)
            if s is not None:
                s.discard(borrower)
                if not s:
                    self._borrowers.pop(oid, None)
        self._maybe_free(oid)

    async def rpc_borrow_add(self, conn: Connection, p):
        self._register_borrower(p["object_id"], tuple(p["borrower"]))
        return {"ok": True}

    async def rpc_wait_ref_removed(self, conn: Connection, p):
        oid = p["object_id"]
        with self._lock:
            st = self._borrow_state.get(oid)
            if st is None or st["count"] <= 0:
                inherited = []
                for child in self._borrowed_via.pop(oid, ()):
                    cst = self._borrow_state.get(child)
                    if cst is not None and cst.get("owner"):
                        inherited.append((child, cst["owner"]))
                return {"removed": True, "inherited": inherited}
            fut = asyncio.get_running_loop().create_future()
            st["waiters"].append(fut)
        try:
            inherited = await asyncio.wait_for(
                fut, cfg.borrower_poll_timeout_s * 0.9
            )
            return {"removed": True, "inherited": inherited}
        except asyncio.TimeoutError:
            return {"removed": False}

    async def rpc_release_return_pins(self, conn: Connection, p):
        """Caller has registered the borrows for refs nested in our returned
        value: drop the pins we held across the handoff."""
        with self._lock:
            pins = self._return_pins.pop(p["task_id"], None)
        for token in pins or ():
            self.unpin_object(token)
        return {}

    async def rpc_reconstruct_object(self, conn: Connection, p):
        """A borrower lost the plasma copy of an object we own: re-execute
        the producing task (ray: object_recovery_manager.h:44)."""
        oid = p["object_id"]
        try:
            fut = await self._reconstruct_owned(oid)
            await asyncio.wait_for(
                asyncio.wrap_future(fut), cfg.object_pull_timeout_s * 2
            )
            return {"ok": True}
        except Exception as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # -- lineage reconstruction ----------------------------------------
    async def _reconstruct_owned(self, oid: bytes) -> concurrent.futures.Future:
        """Resubmit the producing task for a lost owned object. Returns the
        (new) result future; dedupes concurrent reconstructions."""
        with self._lock:
            spec = self._lineage.get(oid)
            if spec is None:
                raise GetTimeoutError(
                    f"object {oid.hex()[:16]} lost and has no lineage "
                    "(puts are not reconstructable)"
                )
            if spec.task_id in self._specs_inflight:
                # Reconstruction (or the original run) already in flight.
                fut = self._futures.get(oid)
                if fut is None:
                    fut = concurrent.futures.Future()
                    self._futures[oid] = fut
                return fut
            if spec.reconstructions >= cfg.max_object_reconstructions:
                raise GetTimeoutError(
                    f"object {oid.hex()[:16]} lost too many times "
                    f"({spec.reconstructions})"
                )
            spec.reconstructions += 1
            spec.attempt += 1
            tid = TaskID(spec.task_id)
            for i in range(1 if spec.num_returns == -1 else spec.num_returns):
                roid = ObjectID.from_index(tid, i + 1).binary()
                self._futures[roid] = concurrent.futures.Future()
            # dynamic item oids (return index >= 2) are not enumerated by
            # num_returns: register the requested one explicitly, replacing
            # a stale done future (its "plasma" result predates the loss)
            if oid not in self._futures or self._futures[oid].done():
                self._futures[oid] = concurrent.futures.Future()
            self._specs_inflight[spec.task_id] = spec
            fut = self._futures[oid]
        logger.info("reconstructing %s via task %s (attempt %d)",
                    oid.hex()[:16], spec.name, spec.attempt)
        try:
            await self.raylet.request(
                "report_lost_object", {"object_id": oid})
        except Exception:
            pass
        # Recursively make sure the task's own args are obtainable.
        for a in list(spec.args) + list(spec.kwargs.values()):
            if a[0] == "r":
                try:
                    await self._ensure_object_available(a[1], a[2] if len(a) > 2 else None)
                except Exception as e:
                    logger.warning("arg recovery for reconstruction failed: %s", e)
        await call_with_retries(
            lambda: self.raylet, "submit_task", {"spec": spec},
            idem=("submit", spec.task_id, spec.attempt),
        )
        return fut

    async def _ensure_object_available(self, oid: bytes, owner=None):
        """Make sure some live node holds oid, reconstructing if needed."""
        locs = []
        try:
            locs = await self.gcs.request(
                "get_object_locations", {"object_id": oid})
        except Exception:
            pass
        if locs:
            return
        if object_store.object_exists(self.store_dir, ObjectID(oid)):
            return
        with self._lock:
            owned = oid in self._owned
        if owned:
            fut = await self._reconstruct_owned(oid)
            await asyncio.wait_for(
                asyncio.wrap_future(fut), cfg.object_pull_timeout_s * 2
            )
        elif owner is not None:
            r = await self._owner_call(
                owner, "reconstruct_object", {"object_id": oid},
                timeout=cfg.object_pull_timeout_s * 2,
            )
            if not r.get("ok"):
                raise GetTimeoutError(
                    f"owner could not recover {oid.hex()[:16]}: {r.get('error')}"
                )

    def _maybe_free(self, oid: bytes):
        with self._lock:
            if oid not in self._owned:
                return
            if self._local_refs.get(oid) or self._escape_pins.get(oid) \
                    or self._borrowers.get(oid):
                return
            tid = ObjectID(oid).task_id().binary()
            if tid in self._specs_inflight:
                return  # producing task still running
            self._owned.discard(oid)
            self._owned_locations.pop(oid, None)
            self._memory_store.pop(oid, None)
            self._futures.pop(oid, None)
            # Lineage is deliberately NOT popped here: a downstream object's
            # reconstruction may need to re-execute this object's producing
            # task too (multi-hop recovery). The FIFO cap in _record_lineage
            # bounds the memory (ray: lineage pinned while reachable).
            contains = self._contains.pop(oid, None)
            buf = self._pinned_buffers.pop(oid, None)
        if buf is not None:
            try:
                buf.release()
            except Exception:
                pass
        for token in contains or ():
            self.unpin_object(token)
        memview.forget_put(oid)  # a freed object is no leak candidate
        # tick-batched frees: ref churn (a put-per-iteration loop) would
        # otherwise fire one RPC + io-loop wakeup per dropped object
        self._free_buf.append(oid)
        if not self._free_flushing:
            self._free_flushing = True
            try:
                self.io.call_soon(self._flush_frees())
            except Exception:
                self._free_flushing = False

    async def _flush_frees(self):
        # debounced: a sequential get loop drops one ref per call, and a
        # flush-per-tick turns that into a free_objects chain (driver ->
        # raylet -> GCS) per call competing with the calls themselves for
        # CPU; the window batches them into one frame. Frees are refcount
        # GC — nothing awaits them — so the only cost is pages staying
        # pinned for the window.
        dt = cfg.free_flush_interval_s
        await asyncio.sleep(dt if dt > 0 else 0)
        buf, self._free_buf = self._free_buf, []
        self._free_flushing = False
        if not buf:
            return
        try:
            await self.raylet.notify("free_objects", {"object_ids": buf})
        except Exception:
            pass

    # ------------------------------------------------------------------
    def node_stats(self):
        return self.io.run(self.raylet.request("node_stats", {}))

    def get_nodes(self):
        return self.io.run(self.gcs.request("get_nodes", {}))

    def disconnect(self):
        self.connected = False
        try:
            for conn in list(self._direct_conns.values()):
                self.io.run(conn.close(), timeout=2)
            for st in list(self._actor_direct.values()):
                if st.get("conn") is not None:
                    self.io.run(st["conn"].close(), timeout=2)
            self.io.run(self.raylet.close(), timeout=2)
            self.io.run(self.gcs.close(), timeout=2)
        except Exception:
            pass
        # release this session's arena state (writer slab mapping, cached
        # reader mappings + flock fds, index mmap) — a long-lived process
        # cycling init()/shutdown() must not pin dead sessions' shm pages
        try:
            if self._slab_writer is not None:
                self._slab_writer.close()
            slab_arena.drop_view(self.store_dir)
        except Exception:
            pass
        self.io.stop()


class Worker:
    """Process-global holder (analog of ray: python/ray/_private/worker.py:410)."""

    def __init__(self):
        self.core_worker: Optional[CoreWorker] = None
        self.node = None  # head Node if we started one
        self.mode: Optional[str] = None

    @property
    def connected(self):
        return self.core_worker is not None and self.core_worker.connected

    def check_connected(self):
        if not self.connected:
            raise RuntimeError("ray_tpu.init() must be called before using the API")


global_worker = Worker()
