"""Core worker: the per-process runtime embedded in drivers and workers.

Analog of the reference's CoreWorker (ray: src/ray/core_worker/core_worker.h:284):
task submission with submitter-side dependency resolution
(ray: transport/dependency_resolver.h — owned in-memory args are awaited and
inlined before the lease request; plasma refs are left for the raylet), an
in-process memory store for small objects (ray: memory_store.h:43), the plasma
provider for shm objects (ray: plasma_store_provider.h:88), owner-side retry
bookkeeping (ray: task_manager.h:173), a simplified reference counter
(ray: reference_count.h:61), and per-caller ordered actor submission
(ray: sequential_actor_submit_queue.h).

Sync user code runs on the main/executor threads; all IO rides a dedicated
asyncio loop thread (rpcio.EventLoopThread), mirroring the reference's
io_context-per-process model.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import object_store, serialization
from ray_tpu._private.common import SchedulingStrategy, TaskSpec, rewrite_resources_for_pg
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpcio import Connection, EventLoopThread, connect

logger = logging.getLogger(__name__)


class GetTimeoutError(TimeoutError):
    pass


class ActorDiedError(RuntimeError):
    pass


class TaskCancelledError(RuntimeError):
    pass


class CoreWorker:
    def __init__(
        self,
        raylet_host: str,
        raylet_port: int,
        gcs_host: str,
        gcs_port: int,
        is_driver: bool,
        job_id: Optional[bytes] = None,
        namespace: Optional[str] = None,
    ):
        self.client_id = WorkerID.from_random().hex()
        self.is_driver = is_driver
        self.namespace = namespace or "default"
        self.executor = None  # set by TaskExecutor on worker processes
        self.io = EventLoopThread(name=f"coreworker-io-{self.client_id[:6]}")
        self.raylet: Connection = self.io.run(
            connect(raylet_host, raylet_port, handler=self, name="raylet-conn")
        )
        self.gcs: Connection = self.io.run(
            connect(gcs_host, gcs_port, handler=self, name="gcs-conn")
        )
        self.gcs_addr = (gcs_host, gcs_port)
        if is_driver and job_id is None:
            job_id = self.io.run(
                self.gcs.request("register_job", {"namespace": self.namespace,
                                                  "driver": {"pid": os.getpid()}})
            )["job_id"]
        self.job_id = job_id or JobID.from_int(0).binary()
        self.io.run(
            self.gcs.request(
                "register_client",
                {"client_id": self.client_id, "job_id": self.job_id,
                 "is_driver": is_driver},
            )
        )
        reply = self.io.run(
            self.raylet.request(
                "register_client",
                {"client_id": self.client_id,
                 "kind": "driver" if is_driver else "worker",
                 "job_id": self.job_id, "pid": os.getpid()},
            )
        )
        self.node_id: str = reply["node_id"]
        self.store_dir: str = reply["store_dir"]
        self.node_resources: Dict[str, float] = reply.get("resources_total", {})
        self.node_labels: Dict[str, str] = reply.get("labels", {})
        self.addr = (self.node_id, self.client_id)
        if is_driver:
            self.task_id = TaskID.for_driver(JobID(self.job_id))
        else:
            self.task_id = TaskID.for_task(JobID(self.job_id))
        # owner-side state
        self._lock = threading.Lock()
        self._futures: Dict[bytes, concurrent.futures.Future] = {}
        self._memory_store: Dict[bytes, Tuple[bytes, bytes]] = {}
        self._pinned_buffers: Dict[bytes, object_store.ObjectBuffer] = {}
        self._specs_inflight: Dict[bytes, TaskSpec] = {}
        self._put_index = 0
        self._local_refs: Dict[bytes, int] = {}
        self._submitted_refs: Dict[bytes, int] = {}
        self._owned: set = set()
        self._borrowed: set = set()
        # Owned objects whose refs were serialized out of this process: a
        # borrower may resolve them at any time, so never auto-free them
        # (conservative stand-in for the reference's borrower protocol,
        # ray: reference_count.h WaitForRefRemoved).
        self._escaped: set = set()
        self._actor_seq: Dict[bytes, int] = {}
        self._pubsub_handlers: Dict[str, list] = {}
        self.connected = True

    # ------------------------------------------------------------------
    # argument encoding / submitter-side dependency resolution
    # ------------------------------------------------------------------
    def _encode_value(self, value: Any) -> Tuple:
        sv = serialization.serialize(value)
        if sv.nested_refs:
            self.pin_escaped(sv.nested_refs)
        if sv.total_data_len <= cfg.max_direct_call_object_size:
            return ("v", sv.metadata, sv.to_bytes())
        ref = self._put_serialized(sv)
        # Keep the implicit put alive until the consuming task finishes.
        self._submitted_refs[ref.binary()] = self._submitted_refs.get(ref.binary(), 0) + 1
        return ("r", ref.binary(), ref.owner)

    def _encode_slots(self, args, kwargs):
        """Encode values eagerly; refs become ('pending', ref) placeholders."""
        enc_args = [
            ("pending", a) if isinstance(a, ObjectRef) else self._encode_value(a)
            for a in args
        ]
        enc_kwargs = {
            k: (("pending", v) if isinstance(v, ObjectRef) else self._encode_value(v))
            for k, v in (kwargs or {}).items()
        }
        pending = [s[1] for s in enc_args if s[0] == "pending"]
        pending += [s[1] for s in enc_kwargs.values() if s[0] == "pending"]
        return enc_args, enc_kwargs, pending

    def _finalize_slot(self, slot):
        if slot[0] != "pending":
            return slot
        ref: ObjectRef = slot[1]
        with self._lock:
            inline = self._memory_store.get(ref.binary())
        if inline is not None:
            return ("v", inline[0], inline[1])
        self._submitted_refs[ref.binary()] = self._submitted_refs.get(ref.binary(), 0) + 1
        return ("r", ref.binary(), ref.owner or self.addr)

    async def _submit_when_ready(self, spec: TaskSpec, enc_args, enc_kwargs,
                                 pending: List[ObjectRef]):
        try:
            for ref in pending:
                fut = self.future_for(ref)
                await asyncio.wait_for(
                    asyncio.wrap_future(fut), cfg.object_pull_timeout_s * 4
                )
        except Exception as e:
            self._fail_returns(spec, f"dependency resolution failed: {e}")
            return
        spec.args = [self._finalize_slot(s) for s in enc_args]
        spec.kwargs = {k: self._finalize_slot(s) for k, s in enc_kwargs.items()}
        try:
            await self.raylet.request("submit_task", {"spec": spec})
        except Exception as e:
            self._fail_returns(spec, f"task submission failed: {e}")

    def _fail_returns(self, spec: TaskSpec, message: str):
        sv = serialization.serialize_error(RuntimeError(message), spec.name)
        tid = TaskID(spec.task_id)
        with self._lock:
            self._specs_inflight.pop(spec.task_id, None)
        for i in range(spec.num_returns):
            oid = ObjectID.from_index(tid, i + 1)
            self._resolve_inline(oid.binary(), sv.metadata, sv.to_bytes())

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit_task(
        self,
        func,
        args=(),
        kwargs=None,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        scheduling: Optional[SchedulingStrategy] = None,
        max_retries: int = 3,
        retry_exceptions: bool = False,
        name: str = "",
        func_blob: Optional[bytes] = None,
        runtime_env: Optional[dict] = None,
    ) -> List[ObjectRef]:
        import cloudpickle

        task_id = TaskID.for_task(JobID(self.job_id))
        scheduling = scheduling or SchedulingStrategy()
        resources = dict(resources if resources is not None else {"CPU": 1.0})
        if scheduling.kind == "PLACEMENT_GROUP":
            resources = rewrite_resources_for_pg(
                resources, scheduling.pg_id, scheduling.pg_bundle_index
            )
        enc_args, enc_kwargs, pending = self._encode_slots(args, kwargs)
        spec = TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id,
            name=name or getattr(func, "__name__", "task"),
            func_blob=func_blob if func_blob is not None else cloudpickle.dumps(func),
            method_name=None,
            num_returns=num_returns,
            resources=resources,
            scheduling=scheduling,
            owner=self.addr,
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            caller_id=self.client_id.encode(),
            runtime_env=runtime_env,
        )
        refs = self._register_returns(spec)
        self.io.call_soon(self._submit_when_ready(spec, enc_args, enc_kwargs, pending))
        return refs

    def _register_returns(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = []
        task_id = TaskID(spec.task_id)
        with self._lock:
            self._specs_inflight[spec.task_id] = spec
            for i in range(spec.num_returns):
                oid = ObjectID.from_index(task_id, i + 1)
                fut = concurrent.futures.Future()
                self._futures[oid.binary()] = fut
                self._owned.add(oid.binary())
                refs.append(ObjectRef(oid, self.addr))
        for r in refs:
            self.add_local_ref(r)
        return refs

    # -- actors ---------------------------------------------------------
    def create_actor(
        self,
        cls,
        args,
        kwargs,
        resources: Dict[str, float],
        scheduling: Optional[SchedulingStrategy] = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: int = 1,
        lifetime: Optional[str] = None,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        runtime_env: Optional[dict] = None,
    ) -> bytes:
        import cloudpickle

        actor_id = ActorID.of(JobID(self.job_id))
        resources = dict(resources)
        scheduling = scheduling or SchedulingStrategy()
        if scheduling.kind == "PLACEMENT_GROUP":
            resources = rewrite_resources_for_pg(
                resources, scheduling.pg_id, scheduling.pg_bundle_index
            )
        enc_args, enc_kwargs, pending = self._encode_slots(args, kwargs)
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(actor_id).binary(),
            job_id=self.job_id,
            name=getattr(cls, "__name__", "Actor"),
            func_blob=cloudpickle.dumps(cls),
            method_name=None,
            resources=resources,
            scheduling=scheduling,
            owner=self.addr,
            actor_id=actor_id.binary(),
            actor_creation=True,
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            max_concurrency=max_concurrency,
            lifetime=lifetime,
            name_registered=name,
            namespace=namespace or self.namespace,
            runtime_env=runtime_env,
            caller_id=self.client_id.encode(),
        )
        if not pending:
            spec.args = [self._finalize_slot(s) for s in enc_args]
            spec.kwargs = {k: self._finalize_slot(s) for k, s in enc_kwargs.items()}
            reply = self.io.run(
                self.gcs.request("register_actor", {"spec": spec}),
                timeout=cfg.gcs_rpc_timeout_s,
            )
            if reply.get("error"):
                raise ValueError(reply["error"])
        else:
            self.io.call_soon(
                self._register_actor_when_ready(spec, enc_args, enc_kwargs, pending)
            )
        return actor_id.binary()

    async def _register_actor_when_ready(self, spec, enc_args, enc_kwargs, pending):
        for ref in pending:
            try:
                await asyncio.wait_for(
                    asyncio.wrap_future(self.future_for(ref)),
                    cfg.object_pull_timeout_s * 4,
                )
            except Exception:
                logger.error("actor %s creation dependency failed", spec.name)
        spec.args = [self._finalize_slot(s) for s in enc_args]
        spec.kwargs = {k: self._finalize_slot(s) for k, s in enc_kwargs.items()}
        await self.gcs.request("register_actor", {"spec": spec})

    def submit_actor_task(
        self,
        actor_id: bytes,
        method_name: str,
        args=(),
        kwargs=None,
        num_returns: int = 1,
        max_task_retries: int = 0,
    ) -> List[ObjectRef]:
        task_id = TaskID.for_actor_task(ActorID(actor_id))
        with self._lock:
            seq = self._actor_seq.get(actor_id, 0)
            self._actor_seq[actor_id] = seq + 1
        enc_args, enc_kwargs, pending = self._encode_slots(args, kwargs)
        spec = TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id,
            name=method_name,
            func_blob=None,
            method_name=method_name,
            num_returns=num_returns,
            resources={},
            owner=self.addr,
            actor_id=actor_id,
            max_retries=max_task_retries,
            seq_no=seq,
            caller_id=self.client_id.encode(),
        )
        refs = self._register_returns(spec)
        self.io.call_soon(self._submit_when_ready(spec, enc_args, enc_kwargs, pending))
        return refs

    def get_actor_table(self, actor_id: Optional[bytes] = None,
                        name: Optional[str] = None, namespace: Optional[str] = None):
        return self.io.run(
            self.gcs.request(
                "get_actor",
                {"actor_id": actor_id, "name": name,
                 "namespace": namespace or self.namespace},
            )
        )

    def wait_actor_alive(self, actor_id: bytes, timeout: float = 60.0):
        return self.io.run(
            self.gcs.request("wait_actor_alive",
                             {"actor_id": actor_id, "timeout": timeout})
        )

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self.io.run(
            self.gcs.request("kill_actor", {"actor_id": actor_id, "no_restart": no_restart})
        )

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        task_id = ref.id().task_id()
        self.io.run(
            self.raylet.request("cancel_task", {"task_id": task_id.binary(), "force": force})
        )

    # ------------------------------------------------------------------
    # owner notifications (results arrive here)
    # ------------------------------------------------------------------
    async def rpc_task_result(self, conn: Connection, p):
        task_id: bytes = p["task_id"]
        with self._lock:
            spec = self._specs_inflight.get(task_id)
            if spec is not None and p.get("attempt", 0) < spec.attempt:
                return  # stale notification from a superseded attempt
        if p.get("error") is not None:
            await self._handle_task_error(spec, task_id, p)
            return
        results = p["results"] or []
        with self._lock:
            self._specs_inflight.pop(task_id, None)
        tid = TaskID(task_id)
        for i, res in enumerate(results):
            oid = ObjectID.from_index(tid, i + 1)
            if res[0] == "v":
                self._resolve_inline(oid.binary(), res[1], res[2])
            else:
                self._resolve_plasma(oid.binary())
        if spec is not None:
            self._release_submitted_refs(spec)
        # Returns whose refs were already dropped can be freed now.
        for i in range(len(results)):
            self._maybe_free(ObjectID.from_index(tid, i + 1).binary())

    def _release_submitted_refs(self, spec: TaskSpec):
        for a in list(spec.args) + list(spec.kwargs.values()):
            if a[0] == "r":
                with self._lock:
                    n = self._submitted_refs.get(a[1], 0) - 1
                    if n <= 0:
                        self._submitted_refs.pop(a[1], None)
                    else:
                        self._submitted_refs[a[1]] = n
                        continue
                self._maybe_free(a[1])

    async def _handle_task_error(self, spec: Optional[TaskSpec], task_id: bytes, p):
        retriable = p.get("retriable", False)
        app_error = p.get("app_error", False)
        if spec is not None and retriable and spec.attempt < spec.max_retries and (
            not app_error or spec.retry_exceptions
        ):
            spec.attempt += 1
            logger.info("retrying task %s (attempt %d)", spec.name, spec.attempt)
            await asyncio.sleep(cfg.task_retry_delay_ms / 1000.0)
            try:
                await self.raylet.request("submit_task", {"spec": spec})
                return
            except Exception:
                pass
        with self._lock:
            self._specs_inflight.pop(task_id, None)
        tid = TaskID(task_id)
        n_returns = spec.num_returns if spec else 1
        if p.get("error_value"):
            meta, data = p["error_value"]
        else:
            if p.get("actor_dead"):
                exc = ActorDiedError(p["error"])
            elif p.get("cancelled"):
                exc = TaskCancelledError(p["error"])
            else:
                exc = RuntimeError(p["error"])
            sv = serialization.serialize_error(exc, spec.name if spec else "")
            meta, data = sv.metadata, sv.to_bytes()
        for i in range(n_returns):
            oid = ObjectID.from_index(tid, i + 1)
            self._resolve_inline(oid.binary(), meta, data)
        if spec is not None:
            self._release_submitted_refs(spec)

    def _resolve_inline(self, oid: bytes, metadata: bytes, data: bytes):
        with self._lock:
            self._memory_store[oid] = (metadata, data)
            fut = self._futures.get(oid)
        if fut and not fut.done():
            fut.set_result(("inline", metadata, data))

    def _resolve_plasma(self, oid: bytes):
        with self._lock:
            fut = self._futures.get(oid)
        if fut and not fut.done():
            fut.set_result(("plasma", None, None))

    # serving borrowers fetching owned values
    async def rpc_fetch_owned(self, conn: Connection, p):
        oid = p["object_id"]
        with self._lock:
            inline = self._memory_store.get(oid)
            fut = self._futures.get(oid)
        if inline is not None:
            return {"inline": inline}
        if fut is not None and fut.done():
            return {"plasma": True}
        if fut is not None:
            return {"pending": True}
        return {"unknown": True}

    async def rpc_pubsub(self, conn: Connection, p):
        for cb in self._pubsub_handlers.get(p["channel"], ()):
            try:
                cb(p["message"])
            except Exception:
                logger.exception("pubsub callback failed")

    # delegated to the executor on worker processes
    async def _await_executor(self):
        while self.executor is None:
            await asyncio.sleep(0.005)
        return self.executor

    async def rpc_execute_task(self, conn: Connection, p):
        ex = await self._await_executor()
        return await ex.execute_task(p["spec"])

    async def rpc_become_actor(self, conn: Connection, p):
        ex = await self._await_executor()
        return await ex.become_actor(p["spec"])

    def rpc_exit(self, conn: Connection, p):
        logging.shutdown()
        os._exit(0)

    def subscribe(self, channel: str, callback):
        self._pubsub_handlers.setdefault(channel, []).append(callback)
        self.io.run(self.gcs.request("subscribe", {"channel": channel}))

    def publish(self, channel: str, message):
        self.io.run(self.gcs.request("publish", {"channel": channel, "message": message}))

    # ------------------------------------------------------------------
    # objects: put/get/wait
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        sv = serialization.serialize(value)
        return self._put_serialized(sv)

    def _put_serialized(self, sv: serialization.SerializedValue) -> ObjectRef:
        if sv.nested_refs:
            self.pin_escaped(sv.nested_refs)
        with self._lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(self.task_id, idx)
        if sv.total_data_len <= cfg.max_direct_call_object_size:
            with self._lock:
                self._memory_store[oid.binary()] = (sv.metadata, sv.to_bytes())
                self._owned.add(oid.binary())
        else:
            object_store.write_object(
                self.store_dir, oid, sv.metadata, sv.buffers, sv.total_data_len
            )
            self.io.run(self.raylet.request("register_put", {"object_id": oid.binary()}))
            with self._lock:
                self._owned.add(oid.binary())
        ref = ObjectRef(oid, self.addr)
        self.add_local_ref(ref)
        return ref

    def future_for(self, ref: ObjectRef) -> concurrent.futures.Future:
        with self._lock:
            fut = self._futures.get(ref.binary())
            if fut is not None:
                return fut
            if ref.binary() in self._memory_store:
                fut = concurrent.futures.Future()
                fut.set_result(("inline",) + self._memory_store[ref.binary()])
                self._futures[ref.binary()] = fut
                return fut
            fut = concurrent.futures.Future()
            self._futures[ref.binary()] = fut
        if object_store.object_exists(self.store_dir, ref.id()):
            if not fut.done():
                fut.set_result(("plasma", None, None))
            return fut
        # Borrowed ref: resolve in background (plasma pull or owner fetch).
        self.io.call_soon(self._resolve_borrowed(ref, fut))
        return fut

    async def _resolve_borrowed(self, ref: ObjectRef, fut: concurrent.futures.Future):
        oid = ref.binary()
        deadline = time.monotonic() + cfg.object_pull_timeout_s
        while time.monotonic() < deadline and not fut.done():
            if object_store.object_exists(self.store_dir, ref.id()):
                if not fut.done():
                    fut.set_result(("plasma", None, None))
                return
            owner = ref.owner
            if owner is not None and tuple(owner) != self.addr:
                try:
                    r = await self.raylet.request(
                        "fetch_owned_routed", {"owner": tuple(owner), "object_id": oid},
                        timeout=10.0,
                    )
                except Exception:
                    r = {}
                if r.get("inline"):
                    meta, data = r["inline"]
                    self._resolve_inline(oid, meta, data)
                    return
                if r.get("plasma"):
                    ok = (await self.raylet.request("pull_object", {"object_id": oid}))["ok"]
                    if ok and not fut.done():
                        fut.set_result(("plasma", None, None))
                        return
                if r.get("pending"):
                    # Producer still running: keep waiting past the deadline.
                    deadline = time.monotonic() + cfg.object_pull_timeout_s
            else:
                try:
                    ok = (await self.raylet.request("pull_object", {"object_id": oid}))["ok"]
                    if ok and not fut.done():
                        fut.set_result(("plasma", None, None))
                        return
                except Exception:
                    pass
            await asyncio.sleep(0.05)
        if not fut.done():
            fut.set_exception(GetTimeoutError(f"could not resolve {ref}"))

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        futs = [self.future_for(r) for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        values = []
        for r, f in zip(refs, futs):
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                kind, meta, data = f.result(remaining)
            except concurrent.futures.TimeoutError:
                raise GetTimeoutError(
                    f"Get timed out: {r} not ready after {timeout}s"
                ) from None
            values.append(self._materialize(r, kind, meta, data))
        return values[0] if single else values

    def _materialize(self, ref: ObjectRef, kind, meta, data):
        if kind == "inline":
            return serialization.deserialize(meta, data)
        oid = ref.id()
        buf = object_store.read_object(self.store_dir, oid)
        if buf is None:
            ok = self.io.run(self.raylet.request("pull_object", {"object_id": ref.binary()}))
            if not ok.get("ok"):
                raise GetTimeoutError(f"object {ref} lost and could not be re-fetched")
            buf = object_store.read_object(self.store_dir, oid)
            if buf is None:
                raise GetTimeoutError(f"object {ref} unavailable")
        with self._lock:
            old = self._pinned_buffers.pop(ref.binary(), None)
            self._pinned_buffers[ref.binary()] = buf
        return serialization.deserialize(buf.metadata, buf.data)

    def wait(self, refs: List[ObjectRef], num_returns=1, timeout=None,
             fetch_local=True):
        futs = {self.future_for(r): r for r in refs}
        deadline = None if timeout is None else time.monotonic() + timeout
        done: set = set()
        while len(done) < num_returns:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining < 0:
                break
            d, _ = concurrent.futures.wait(
                [f for f in futs if f not in done], timeout=remaining,
                return_when=concurrent.futures.FIRST_COMPLETED,
            )
            if not d:
                break
            done |= d
        ready_set = {futs[f] for f in done}
        ordered_ready = [r for r in refs if r in ready_set][:num_returns]
        picked = set(ordered_ready)
        not_ready = [r for r in refs if r not in picked]
        return ordered_ready, not_ready

    # ------------------------------------------------------------------
    # reference counting (simplified; ray: reference_count.h:61)
    # ------------------------------------------------------------------
    def add_local_ref(self, ref: ObjectRef):
        with self._lock:
            self._local_refs[ref.binary()] = self._local_refs.get(ref.binary(), 0) + 1
        ref._counted = True  # __del__ releases this count

    def remove_local_ref(self, ref_binary: bytes):
        with self._lock:
            n = self._local_refs.get(ref_binary, 0) - 1
            if n <= 0:
                self._local_refs.pop(ref_binary, None)
            else:
                self._local_refs[ref_binary] = n
                return
        self._maybe_free(ref_binary)

    def register_borrowed_ref(self, ref: ObjectRef):
        with self._lock:
            self._borrowed.add(ref.binary())

    def pin_escaped(self, nested_refs):
        """Pin owned objects whose refs are leaving this process."""
        with self._lock:
            for binary, _owner in nested_refs:
                if binary in self._owned:
                    self._escaped.add(binary)

    def _maybe_free(self, oid: bytes):
        with self._lock:
            if oid not in self._owned or oid in self._escaped:
                return
            if self._local_refs.get(oid) or self._submitted_refs.get(oid):
                return
            if oid in self._specs_inflight:
                return
            self._owned.discard(oid)
            self._memory_store.pop(oid, None)
            self._futures.pop(oid, None)
            buf = self._pinned_buffers.pop(oid, None)
        if buf is not None:
            try:
                buf.release()
            except Exception:
                pass
        try:
            self.io.call_soon(self.raylet.request("free_object", {"object_id": oid}))
        except Exception:
            pass

    # ------------------------------------------------------------------
    def node_stats(self):
        return self.io.run(self.raylet.request("node_stats", {}))

    def get_nodes(self):
        return self.io.run(self.gcs.request("get_nodes", {}))

    def disconnect(self):
        self.connected = False
        try:
            self.io.run(self.raylet.close(), timeout=2)
            self.io.run(self.gcs.close(), timeout=2)
        except Exception:
            pass
        self.io.stop()


class Worker:
    """Process-global holder (analog of ray: python/ray/_private/worker.py:410)."""

    def __init__(self):
        self.core_worker: Optional[CoreWorker] = None
        self.node = None  # head Node if we started one
        self.mode: Optional[str] = None

    @property
    def connected(self):
        return self.core_worker is not None and self.core_worker.connected

    def check_connected(self):
        if not self.connected:
            raise RuntimeError("ray_tpu.init() must be called before using the API")


global_worker = Worker()
