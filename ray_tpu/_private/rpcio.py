"""Bidirectional async RPC substrate.

Plays the role of the reference's gRPC + asio layer (ray: src/ray/rpc/,
src/ray/common/asio/): every control-plane process (GCS, raylet, core worker)
runs one asyncio loop; peers hold persistent duplex connections over which
either side can issue requests or one-way notifications. Two frame formats
exist, negotiated per connection (see the auth preamble below):

  v1: ``[4B len][pickle((msg_id, kind, method, payload))]``
  v2: ``[4B total_len][1B nbufs][4B len x nbufs][pickle5 envelope][buf0]...``

v2 is the zero-copy out-of-band format: the envelope is pickled with a
``buffer_callback`` so large buffers (numpy arrays, shm chunk views,
``serialization.BufferList`` members) are never memcpy'd into the pickle
stream — the flush path writes them to the socket as vectored memoryviews,
and the receiver reconstructs zero-copy memoryviews over a single read
buffer. This makes the connection a data plane too: object-manager chunks
and inline task args/results ride frames without per-hop copies, while the
shm store stays the intra-node zero-copy path.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import hashlib
import hmac
import itertools
import logging
import os
import pickle
import threading
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

KIND_REQ = 0
KIND_RESP = 1
KIND_ERR = 2
KIND_NOTIFY = 3

_HDR = 4
# frames above this size are written unjoined (joining would memcpy MBs);
# smaller parts coalesce into one socket write per tick
_JOIN_MAX = 128 * 1024
# v2 buffer table: 1-byte count field caps out-of-band buffers per frame;
# overflow buffers simply stay in-band (correct, one extra copy)
_MAX_OOB_BUFS = 255

_HAS_EAGER_FACTORY = hasattr(asyncio, "eager_task_factory")


def _max_msg() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.rpc_max_message_bytes


def _oob_min() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.rpc_oob_min_bytes


def _frame_version() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.rpc_frame_version


def _nbytes(part) -> int:
    return part.nbytes if isinstance(part, memoryview) else len(part)

# --- connection authentication -----------------------------------------
# Frames are pickles, and unpickling executes code — so no frame may be
# read from an unauthenticated peer. Every client opens with a fixed-size
# raw preamble [5B magic][64B sha256(token) hex] before any pickle frame;
# the server closes mismatching connections without ever unpickling their
# bytes. The token is RAY_TPU_CLUSTER_TOKEN (the head node generates one
# at startup and propagates it through package_env; remote drivers export
# it). The preamble is sent unconditionally — with an empty token it
# hashes "" — so a token-bearing client and a token-less server can never
# misparse each other's streams; they fail the digest compare and close.
# Plays the role of the reference's cluster auth token scoping.
#
# Threat model: this is a static bearer credential on a trusted LAN — it
# scopes which processes belong to the cluster and keeps stray/stale
# processes from delivering pickles. It is NOT a defense against an
# on-path network attacker: there is no nonce/challenge (an observed
# preamble replays) and clients do not authenticate the server. That
# matches the reference's cluster-token posture; deployments that face
# untrusted networks must wrap transport in TLS/VPN at a lower layer.
#
# Frame-version negotiation rides the preamble's magic: a client that
# speaks the v2 out-of-band frame format opens with magic "RTPU2" (same
# preamble length); a v2-aware server answers with a single version byte
# 0x02 and both sides speak v2 from the first frame. A v1-only server
# fails the digest compare on the unknown magic and closes — the client
# detects the EOF where the version byte should be and redials with the
# v1 preamble, so mixed-version clusters never misparse streams. A v1
# client sending "RTPU1" gets a silent (byte-free) v1 session from a v2
# server, exactly as before.

_AUTH_MAGIC = b"RTPU1"
_AUTH_MAGIC_V2 = b"RTPU2"
_AUTH_LEN = len(_AUTH_MAGIC) + 64
_V2_ACK = b"\x02"


def cluster_token() -> str:
    return os.environ.get("RAY_TPU_CLUSTER_TOKEN", "")


def _auth_preamble(token: str, version: int = 1) -> bytes:
    digest = hashlib.sha256(token.encode()).hexdigest().encode()
    return (_AUTH_MAGIC_V2 if version >= 2 else _AUTH_MAGIC) + digest


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class Finalized:
    """Handler-return wrapper: ``payload`` is sent as the response, then
    ``release()`` runs once the frame has been handed to the transport —
    for responses carrying zero-copy views over resources that must
    outlive the write (e.g. mmap'd object-store chunks)."""

    __slots__ = ("payload", "release")

    def __init__(self, payload, release: Callable[[], None]):
        self.payload = payload
        self.release = release


def _decode_v2(data: bytes):
    """Decode a v2 frame body (everything after the 4B total-length header)
    into ``(msg_id, kind, method, payload)``. Out-of-band buffers become
    zero-copy memoryviews over ``data`` — they stay valid (and readonly)
    for as long as the payload holds them, independent of the connection."""
    if len(data) < 1:
        raise RpcError("corrupt v2 frame: empty body")
    nbufs = data[0]
    view = memoryview(data)
    if nbufs == 0:  # control-plane common case: no table to parse
        return pickle.loads(view[1:])
    env_start = 1 + 4 * nbufs
    if env_start > len(data):
        raise RpcError("corrupt v2 frame: buffer table truncated")
    lens = [
        int.from_bytes(view[1 + 4 * i: 5 + 4 * i], "little")
        for i in range(nbufs)
    ]
    env_end = len(data) - sum(lens)
    if env_end < env_start:
        raise RpcError("corrupt v2 frame: buffers exceed frame length")
    bufs = []
    pos = env_end
    for n in lens:
        bufs.append(view[pos: pos + n])
        pos += n
    return pickle.loads(view[env_start:env_end], buffers=bufs)


class Connection:
    """One duplex peer connection. Owned by exactly one event loop."""

    _ids = itertools.count(1)

    def __init__(self, reader, writer, handler: Optional[object] = None,
                 name: str = "?", version: int = 1):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        # negotiated frame format (1 = in-band pickle, 2 = out-of-band
        # buffer table); both peers agreed on it during the auth preamble
        self.version = version
        # flags read once per connection: the recv/send loops are hot paths
        self._max_msg = _max_msg()
        self._oob_min = _oob_min()
        self._pending: Dict[int, asyncio.Future] = {}
        self._msg_ids = itertools.count(1)
        self._send_lock = asyncio.Lock()
        # tick-coalesced writes: frames queued in order, one flush task
        # joins small frames into a single socket write per loop tick
        self._wbuf: list = []
        self._wflush: Optional[asyncio.Task] = None
        self._closed = False
        self.on_close: Optional[Callable] = None
        self._recv_task: Optional[asyncio.Task] = None
        # Arbitrary peer metadata attached at registration time.
        self.meta: Dict[str, Any] = {}

    def start(self):
        self._recv_task = asyncio.get_running_loop().create_task(self._recv_loop())
        return self._recv_task

    def _enqueue_frame(self, parts: tuple) -> asyncio.Task:
        """Queue one frame's parts synchronously (caller order = wire
        order) and return the shared flush task."""
        self._wbuf.append(parts)
        if self._wflush is None or self._wflush.done():
            self._wflush = asyncio.get_running_loop().create_task(
                self._flush_writes()
            )
        return self._wflush

    def _encode_frame(self, msg_id: int, kind: int, method: str,
                      payload) -> tuple:
        """Encode one frame as a tuple of bytes-like parts (written to the
        socket in order, large parts by reference — no join memcpy).

        v1: one part, ``[4B len][pickle]``.
        v2: ``[4B total][1B nbufs][4B len x nbufs][envelope]`` as the head
        part, then each out-of-band buffer as its own part. The envelope is
        pickled with ``buffer_callback`` so protocol-5-aware payloads
        (numpy arrays, PickleBuffers, serialization.BufferList members)
        never enter the pickle stream.

        Raises RpcError BEFORE anything is queued when the frame would
        exceed ``rpc_max_message_bytes`` — an oversized send must fail
        loudly at the caller, not opaquely kill the peer's recv loop.
        """
        if self.version < 2:
            data = pickle.dumps((msg_id, kind, method, payload), protocol=5)
            total = len(data)
            if total > self._max_msg:
                raise RpcError(
                    f"outgoing {method!r} message too large: {total} bytes "
                    f"> rpc_max_message_bytes={self._max_msg}"
                )
            return (total.to_bytes(_HDR, "little") + data,)
        bufs: list = []
        oob_min = self._oob_min

        def _cb(pb: pickle.PickleBuffer):
            try:
                view = pb.raw()
            except Exception:
                return True  # non-contiguous buffer: serialize in-band
            if view.nbytes < oob_min or len(bufs) >= _MAX_OOB_BUFS \
                    or view.nbytes > 0xFFFFFFFF:
                return True  # tiny / table-overflow / >4GiB: in-band
            bufs.append(view)
            return False

        env = pickle.dumps((msg_id, kind, method, payload), protocol=5,
                           buffer_callback=_cb)
        if not bufs:
            # control-plane common case: no table, same cost as a v1 frame
            total = 1 + len(env)
            if total > self._max_msg:
                raise RpcError(
                    f"outgoing {method!r} message too large: {total} bytes "
                    f"> rpc_max_message_bytes={self._max_msg}"
                )
            return (total.to_bytes(_HDR, "little") + b"\x00" + env,)
        table = b"".join(v.nbytes.to_bytes(4, "little") for v in bufs)
        total = 1 + len(table) + len(env) + sum(v.nbytes for v in bufs)
        if total > self._max_msg:
            raise RpcError(
                f"outgoing {method!r} message too large: {total} bytes "
                f"({len(bufs)} out-of-band buffers) "
                f"> rpc_max_message_bytes={self._max_msg}"
            )
        head = b"".join(
            (total.to_bytes(_HDR, "little"), bytes((len(bufs),)), table, env)
        )
        return (head, *bufs)

    async def _send(self, msg_id: int, kind: int, method: str, payload):
        flush = self._enqueue_frame(
            self._encode_frame(msg_id, kind, method, payload)
        )
        # await the shared flush so callers keep drain() backpressure;
        # shield: one canceled sender must not kill everyone's flush
        await asyncio.shield(flush)

    def request_nowait(self, method: str, payload=None) -> asyncio.Future:
        """Enqueue a request frame SYNCHRONOUSLY and return the response
        future. Two request_nowait calls from the same tick hit the wire
        in call order — the ordered-pipelining primitive direct actor
        calls ride on (a plain ``await request()`` per call would
        serialize to one call per RTT or lose ordering across tasks)."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        msg_id = next(self._msg_ids)
        # encode before registering the future: an oversized frame raises
        # here and must not leave a pending entry behind
        parts = self._encode_frame(msg_id, KIND_REQ, method, payload)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        fut.add_done_callback(lambda _f: self._pending.pop(msg_id, None))
        self._enqueue_frame(parts)
        return fut

    async def _flush_writes(self):
        """Write every queued frame with ONE socket write per tick (frames
        stay in queue order — actor-call ordering rides on it). asyncio's
        transport issues a send syscall per write() when its buffer is
        empty, so a burst of small control frames written individually
        costs a syscall + receiver wakeup each; joined, the burst is one
        syscall and the peer's recv loop drains it in one poll."""
        # Explicit yield so the flush always runs past the currently
        # executing callback: under the loops' EAGER task factory,
        # create_task would otherwise run this body synchronously inside
        # the first _enqueue_frame and flush one-frame "bursts". Without
        # an eager factory (<=3.11) create_task already defers to the next
        # loop pass — the yield would only add a scheduling hop per burst.
        if _HAS_EAGER_FACTORY:
            await asyncio.sleep(0)
        async with self._send_lock:
            # loop until drained: frames appended while we're suspended in
            # drain() ride THIS task — a sender that sees the task not done
            # won't start another, so leaving them would stall delivery
            while self._wbuf and not self._closed:
                buf, self._wbuf = self._wbuf, []
                run: list = []
                for frame in buf:
                    # a frame is a tuple of parts (v2 out-of-band buffers
                    # ride as separate memoryview parts, by reference)
                    for part in frame if isinstance(frame, tuple) \
                            else (frame,):
                        if _nbytes(part) > _JOIN_MAX:
                            # big part (object chunk / tensor): joining
                            # would memcpy MBs — flush the small run in
                            # order, then hand the view to the transport
                            if run:
                                self.writer.write(b"".join(run))
                                run = []
                            self.writer.write(part)
                        else:
                            run.append(part)
                if run:
                    self.writer.write(
                        run[0] if len(run) == 1 else b"".join(run)
                    )
                await self.writer.drain()

    async def request(self, method: str, payload=None, timeout: float = None) -> Any:
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        msg_id = next(self._msg_ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            await self._send(msg_id, KIND_REQ, method, payload)
            if timeout:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(msg_id, None)

    async def notify(self, method: str, payload=None):
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        await self._send(0, KIND_NOTIFY, method, payload)

    async def _recv_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(_HDR)
                n = int.from_bytes(hdr, "little")
                if n > self._max_msg:
                    raise RpcError(f"oversized message: {n}")
                data = await self.reader.readexactly(n)
                if self.version >= 2:
                    # ONE read buffer per frame; payload buffers are
                    # zero-copy memoryviews into it (they keep it alive)
                    msg_id, kind, method, payload = _decode_v2(data)
                else:
                    msg_id, kind, method, payload = pickle.loads(data)
                if kind == KIND_RESP:
                    fut = self._pending.get(msg_id)
                    if fut and not fut.done():
                        fut.set_result(payload)
                elif kind == KIND_ERR:
                    fut = self._pending.get(msg_id)
                    if fut and not fut.done():
                        fut.set_exception(RpcError(payload))
                else:
                    # spawn (strong ref): a GC'd dispatch task would drop
                    # the request without ever sending a reply
                    spawn(self._dispatch(msg_id, kind, method, payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("rpc recv loop error on %s", self.name)
        finally:
            await self._do_close()

    async def _dispatch(self, msg_id: int, kind: int, method: str, payload):
        task = asyncio.current_task()
        if task is not None:
            # name = the method being served: SIGUSR2 task dumps then show
            # WHICH handler a wedged dispatch is stuck in, not just that
            # one is stuck (negligible cost next to unpickle+handler)
            task.set_name(f"dispatch:{method}:{self.name}")
        handler = self.handler
        fn = getattr(handler, f"rpc_{method}", None) if handler else None
        if fn is None:
            if kind == KIND_REQ:
                await self._send(msg_id, KIND_ERR, method, f"no handler for {method!r}")
            else:
                logger.warning("%s: dropping notify %r (no handler)", self.name, method)
            return
        release = None
        try:
            result = fn(self, payload)
            if asyncio.iscoroutine(result):
                result = await result
            if isinstance(result, Finalized):
                release = result.release
                result = result.payload
            if kind == KIND_REQ:
                await self._send(msg_id, KIND_RESP, method, result)
        except (ConnectionLost, ConnectionResetError, BrokenPipeError):
            pass
        except Exception as e:
            logger.exception("handler %s failed on %s", method, self.name)
            if kind == KIND_REQ:
                try:
                    await self._send(msg_id, KIND_ERR, method, f"{type(e).__name__}: {e}")
                except Exception:
                    pass
        finally:
            if release is not None:
                # the response frame is past _send (handed to the
                # transport); drop our own reference to the payload so its
                # buffer views die and release() can close the resource
                # (e.g. an ObjectBuffer mmap) instead of deferring to GC
                result = None
                try:
                    release()
                except Exception:
                    logger.exception("response finalizer failed for %s", method)

    async def _do_close(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            try:
                result = self.on_close(self)
                if asyncio.iscoroutine(result):
                    await result
            except Exception:
                logger.exception("on_close callback failed for %s", self.name)

    async def close(self):
        if self._recv_task:
            self._recv_task.cancel()
        await self._do_close()

    @property
    def closed(self):
        return self._closed


class RpcServer:
    """Asyncio TCP server; each accepted peer becomes a Connection with the
    given handler. The handler may implement ``on_connection(conn)`` /
    ``on_disconnect(conn)``."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()

    async def start(self):
        self._server = await asyncio.start_server(self._accept, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _accept(self, reader, writer):
        from ray_tpu._private.config import GLOBAL_CONFIG

        try:
            preamble = await asyncio.wait_for(
                reader.readexactly(_AUTH_LEN), GLOBAL_CONFIG.rpc_auth_timeout_s
            )
        except Exception:
            writer.close()
            return
        # run BOTH digest compares unconditionally (constant-time-ish); the
        # magic picks the negotiated frame version
        token = cluster_token()
        is_v2 = hmac.compare_digest(preamble, _auth_preamble(token, 2))
        is_v1 = hmac.compare_digest(preamble, _auth_preamble(token, 1))
        if not (is_v1 or is_v2):
            logger.warning("rejecting unauthenticated peer on :%d", self.port)
            writer.close()
            return
        version = 2 if is_v2 else 1
        if version >= 2:
            # version byte after the preamble: confirms v2 to the client
            # (a v1 server would instead have closed the connection)
            writer.write(_V2_ACK)
        conn = Connection(reader, writer, self.handler,
                          name=f"server:{self.port}", version=version)
        self.connections.add(conn)

        def _closed(c):
            self.connections.discard(c)
            cb = getattr(self.handler, "on_disconnect", None)
            if cb:
                return cb(c)

        conn.on_close = _closed
        cb = getattr(self.handler, "on_connection", None)
        if cb:
            result = cb(conn)
            if asyncio.iscoroutine(result):
                await result
        conn.start()

    async def stop(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()


async def connect(host: str, port: int, handler=None, name: str = "client",
                  retries: int = None, retry_delay: float = None,
                  token: Optional[str] = None,
                  version: Optional[int] = None) -> Connection:
    """``token`` overrides the ambient cluster token for THIS connection —
    the path to external services with their own credential (the remote
    KV metadata server, like Redis with requirepass).

    ``version`` pins the frame format (default: the rpc_frame_version
    flag). A v2 dial that the peer rejects — a pre-v2 server closes the
    connection at the digest compare — falls back to a fresh v1 dial, so
    mixed-version clusters interoperate for one release."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    if retries is None:
        retries = GLOBAL_CONFIG.rpc_connect_retries
    if retry_delay is None:
        retry_delay = GLOBAL_CONFIG.rpc_connect_retry_delay_s
    want = _frame_version() if version is None else version
    last = None
    for _ in range(retries):
        try:
            reader, writer = await asyncio.open_connection(host, port)
            tok = cluster_token() if token is None else token
            negotiated = 1
            if want >= 2:
                writer.write(_auth_preamble(tok, 2))
                await writer.drain()
                try:
                    ack = await asyncio.wait_for(
                        reader.readexactly(1),
                        GLOBAL_CONFIG.rpc_auth_timeout_s,
                    )
                except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                        ConnectionResetError, OSError) as e:
                    # peer closed instead of acking: a v1-only server (or a
                    # token mismatch — v1 surfaces those on first use too).
                    # Redial speaking v1.
                    try:
                        writer.close()
                    except Exception:
                        pass
                    want = 1
                    raise ConnectionRefusedError(
                        f"v2 handshake refused: {e!r}") from None
                if ack != _V2_ACK:
                    try:
                        writer.close()
                    except Exception:
                        pass
                    raise ConnectionLost(
                        f"bad version ack from {host}:{port}: {ack!r}"
                    )
                negotiated = 2
            else:
                writer.write(_auth_preamble(tok, 1))
                await writer.drain()
            conn = Connection(reader, writer, handler, name=name,
                              version=negotiated)
            # Client-side conns get disconnect callbacks too (raylet/worker
            # GCS-reconnect loops key off this).
            cb = getattr(handler, "on_disconnect", None)
            if cb is not None:
                conn.on_close = cb
            conn.start()
            return conn
        except (ConnectionRefusedError, OSError) as e:
            last = e
            await asyncio.sleep(retry_delay)
    raise ConnectionLost(f"cannot connect to {host}:{port}: {last}")


_BG_TASKS: set = set()


def spawn(coro, name: str = None) -> asyncio.Task:
    """create_task with a STRONG reference held until completion, plus
    dropped-exception logging. The event loop keeps only weak task refs: a
    fire-and-forget task awaiting a future that is reachable only from the
    task itself forms an unrooted cycle the GC may collect mid-await —
    silently skipping the coroutine's finally blocks. (Observed in round 4:
    a collected pump task left its registry key behind and stranded every
    subsequent task of its scheduling class.) Every fire-and-forget
    create_task in system processes must go through here or an equivalent
    live structure."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    if task.done():
        # Eager task factory: the coroutine ran to completion synchronously
        # inside create_task — registering the done-callback AFTER adding to
        # _BG_TASKS would fire it immediately (discard before add) and leak
        # the entry forever. Log any exception and skip the registry.
        if not task.cancelled() and task.exception() is not None:
            logger.error("background task %s failed: %r", task.get_name(),
                         task.exception(), exc_info=task.exception())
        return task
    _BG_TASKS.add(task)

    def _done(t):
        _BG_TASKS.discard(t)
        if not t.cancelled() and t.exception() is not None:
            logger.error("background task %s failed: %r", t.get_name(),
                         t.exception(), exc_info=t.exception())

    task.add_done_callback(_done)
    return task


def enable_eager_tasks(loop: asyncio.AbstractEventLoop):
    """Python 3.12 eager task execution: a new task runs synchronously
    until its first suspension instead of paying a full loop round-trip
    before its first byte of work. For the control plane's short RPC
    dispatch handlers this removes one scheduling hop per message — the
    dominant per-op cost the BENCH_CORE analysis identified. Code that
    NEEDS deferred execution must make it explicit (``_flush_writes``
    leads with ``await asyncio.sleep(0)``)."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is not None:
        loop.set_task_factory(factory)


def _log_dropped_exception(fut) -> None:
    try:
        exc = fut.exception()
    except (asyncio.CancelledError, concurrent.futures.CancelledError):
        return
    if exc is not None:
        logger.error("fire-and-forget coroutine failed: %r", exc,
                     exc_info=exc)


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread, for sync callers.

    This is the analog of the reference's per-process io_context thread
    (ray: src/ray/common/asio/instrumented_io_context.h) embedded in a
    synchronous Python driver/worker.
    """

    def __init__(self, name: str = "rpc-io"):
        self.loop = asyncio.new_event_loop()
        enable_eager_tasks(self.loop)
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        if os.environ.get("RAY_TPU_PROFILE_DIR"):
            from ray_tpu._private.profiling import maybe_profile_thread

            maybe_profile_thread(f"ioloop-{self.thread.name}")
        self.loop.run_forever()

    def run(self, coro, timeout: float = None):
        """Run coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, coro):
        if not self.loop.is_running():
            # Shutdown race: close the coroutine (avoids the un-awaited
            # warning) but RAISE — a silent drop would hang any caller
            # blocking on a future this coroutine was meant to resolve
            # (e.g. worker._resolve_owned_missing). Fire-and-forget call
            # sites already wrap call_soon in try/except.
            coro.close()
            raise RuntimeError("event loop is stopped")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        # Fire-and-forget callers never .result() this future, and
        # run_coroutine_threadsafe swallows coroutine exceptions into it —
        # a crashed submit/registration coroutine would strand its task
        # forever with no trace. Surface the loss loudly instead.
        fut.add_done_callback(_log_dropped_exception)
        return fut

    def stop(self):
        if self.thread.is_alive() and self.loop.is_running():
            self._drain_tasks()
        # ALWAYS queue the stop + join while the thread lives: a loop that
        # has not reached run_forever yet still executes queued callbacks
        # once it starts, so this is the path that keeps an early-shutdown
        # worker from leaking a spinning io thread
        if self.thread.is_alive():
            try:
                self.loop.call_soon_threadsafe(self.loop.stop)
                self.thread.join(timeout=5)
            except Exception:
                pass

    def _drain_tasks(self):
        async def _drain():
            tasks = [t for t in asyncio.all_tasks(self.loop)
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            # let cancellations actually RUN: stopping the loop with
            # cancelled-but-unfinished tasks makes their destructors spam
            # "Task was destroyed but it is pending!" on every shutdown
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            asyncio.run_coroutine_threadsafe(_drain(), self.loop).result(
                timeout=2.0
            )
        except Exception:
            pass
