"""Bidirectional async RPC substrate.

Plays the role of the reference's gRPC + asio layer (ray: src/ray/rpc/,
src/ray/common/asio/): every control-plane process (GCS, raylet, core worker)
runs one asyncio loop; peers hold persistent duplex connections over which
either side can issue requests or one-way notifications. Two frame formats
exist, negotiated per connection (see the auth preamble below):

  v1: ``[4B len][pickle((msg_id, kind, method, payload))]``
  v2: ``[4B total_len][1B nbufs][4B len x nbufs][pickle5 envelope][buf0]...``
  v3: v2 plus a 4-byte CRC32 trailer on the frame head:
      ``[4B total][1B nbufs][4B len x nbufs][envelope][4B crc][buf0]...``

v2 is the zero-copy out-of-band format: the envelope is pickled with a
``buffer_callback`` so large buffers (numpy arrays, shm chunk views,
``serialization.BufferList`` members) are never memcpy'd into the pickle
stream — the flush path writes them to the socket as vectored memoryviews,
and the receiver reconstructs zero-copy memoryviews over a single read
buffer. This makes the connection a data plane too: object-manager chunks
and inline task args/results ride frames without per-hop copies, while the
shm store stays the intra-node zero-copy path.

v3 adds the control-plane hardening layer (the reference gates releases on
RPC-level chaos; see faultsim.py):

  * frame integrity: the CRC32 trailer covers the frame HEAD (count byte,
    buffer table, pickle envelope) — everything that steers parsing and
    dispatch. Out-of-band payload buffers are excluded on purpose: they are
    multi-MB tensors whose checksum would re-scan memory the zero-copy path
    exists to avoid (TCP's checksum still covers them in transit). A CRC
    mismatch raises FrameCorruptError and resets the connection — a typed,
    loud failure instead of unpickling garbage.
  * per-request deadlines: ``request()`` applies ``rpc_request_timeout_s``
    when the caller passes no timeout, raising RpcTimeoutError (a subclass
    of asyncio.TimeoutError, so existing handlers keep matching) — no
    control-plane call can hang forever on a silent peer.
  * keepalive: idle connections exchange ``__ping``/``__pong`` notifies
    every ``rpc_keepalive_interval_s``; no inbound frame for
    ``rpc_keepalive_timeout_s`` declares the peer dead (a black-holed peer
    is detected in O(timeout) instead of hanging a request forever).
  * duplicate suppression: the receiver drops request frames whose msg_id
    was already dispatched on the same connection (wire-level duplication),
    and ``request(..., idem=token)`` registers the call in a process-wide
    idempotency cache so a RETRY on a fresh connection cannot double-execute
    a side-effectful handler — the receiver replays the first execution's
    result instead.
  * ``call_with_retries``: exponential-backoff retry for control-plane
    calls; side-effectful methods must pass an ``idem`` token.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import hashlib
import hmac
import itertools
import logging
import os
import pickle
import random
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional

from ray_tpu._private import faultsim

logger = logging.getLogger(__name__)

KIND_REQ = 0
KIND_RESP = 1
KIND_ERR = 2
KIND_NOTIFY = 3

_HDR = 4
# frames above this size are written unjoined (joining would memcpy MBs);
# smaller parts coalesce into one socket write per tick
_JOIN_MAX = 128 * 1024
# v2 buffer table: 1-byte count field caps out-of-band buffers per frame;
# overflow buffers simply stay in-band (correct, one extra copy)
_MAX_OOB_BUFS = 255

_HAS_EAGER_FACTORY = hasattr(asyncio, "eager_task_factory")


def _max_msg() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.rpc_max_message_bytes


def _oob_min() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.rpc_oob_min_bytes


def _frame_version() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.rpc_frame_version


def _nbytes(part) -> int:
    return part.nbytes if isinstance(part, memoryview) else len(part)

# --- connection authentication -----------------------------------------
# Frames are pickles, and unpickling executes code — so no frame may be
# read from an unauthenticated peer. Every client opens with a fixed-size
# raw preamble [5B magic][64B sha256(token) hex] before any pickle frame;
# the server closes mismatching connections without ever unpickling their
# bytes. The token is RAY_TPU_CLUSTER_TOKEN (the head node generates one
# at startup and propagates it through package_env; remote drivers export
# it). The preamble is sent unconditionally — with an empty token it
# hashes "" — so a token-bearing client and a token-less server can never
# misparse each other's streams; they fail the digest compare and close.
# Plays the role of the reference's cluster auth token scoping.
#
# Threat model: this is a static bearer credential on a trusted LAN — it
# scopes which processes belong to the cluster and keeps stray/stale
# processes from delivering pickles. It is NOT a defense against an
# on-path network attacker: there is no nonce/challenge (an observed
# preamble replays) and clients do not authenticate the server. That
# matches the reference's cluster-token posture; deployments that face
# untrusted networks must wrap transport in TLS/VPN at a lower layer.
#
# Frame-version negotiation rides the preamble's magic: a client that
# speaks the v2 out-of-band frame format opens with magic "RTPU2" (same
# preamble length); a v2-aware server answers with a single version byte
# 0x02 and both sides speak v2 from the first frame. A v1-only server
# fails the digest compare on the unknown magic and closes — the client
# detects the EOF where the version byte should be and redials with the
# next-lower preamble, so mixed-version clusters never misparse streams.
# A v1 client sending "RTPU1" gets a silent (byte-free) v1 session from a
# newer server, exactly as before. v3 ("RTPU3", ack 0x03) is v2 framing
# plus the CRC32 head trailer; the downgrade chain is 3 -> 2 -> 1.

_AUTH_MAGIC = b"RTPU1"
_AUTH_MAGIC_V2 = b"RTPU2"
_AUTH_MAGIC_V3 = b"RTPU3"
_AUTH_LEN = len(_AUTH_MAGIC) + 64
_V2_ACK = b"\x02"
_V3_ACK = b"\x03"
_MAGICS = {1: _AUTH_MAGIC, 2: _AUTH_MAGIC_V2, 3: _AUTH_MAGIC_V3}
_ACKS = {2: _V2_ACK, 3: _V3_ACK}


def cluster_token() -> str:
    return os.environ.get("RAY_TPU_CLUSTER_TOKEN", "")


def _auth_preamble(token: str, version: int = 1) -> bytes:
    digest = hashlib.sha256(token.encode()).hexdigest().encode()
    return _MAGICS[min(version, 3)] + digest


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class RpcTimeoutError(RpcError, asyncio.TimeoutError):
    """A request exceeded its deadline. Subclasses asyncio.TimeoutError so
    pre-existing ``except asyncio.TimeoutError`` call sites keep working."""


class FrameCorruptError(ConnectionLost):
    """An inbound frame failed its integrity check (CRC mismatch or a
    structurally impossible header). The connection is reset: after one
    corrupt frame the stream offset can no longer be trusted."""


class Finalized:
    """Handler-return wrapper: ``payload`` is sent as the response, then
    ``release()`` runs once the frame has been handed to the transport —
    for responses carrying zero-copy views over resources that must
    outlive the write (e.g. mmap'd object-store chunks)."""

    __slots__ = ("payload", "release")

    def __init__(self, payload, release: Callable[[], None]):
        self.payload = payload
        self.release = release


def _decode_v2(data: bytes):
    """Decode a v2 frame body (everything after the 4B total-length header)
    into ``(msg_id, kind, method, payload)``. Out-of-band buffers become
    zero-copy memoryviews over ``data`` — they stay valid (and readonly)
    for as long as the payload holds them, independent of the connection."""
    if len(data) < 1:
        raise RpcError("corrupt v2 frame: empty body")
    nbufs = data[0]
    view = memoryview(data)
    if nbufs == 0:  # control-plane common case: no table to parse
        return pickle.loads(view[1:])
    env_start = 1 + 4 * nbufs
    if env_start > len(data):
        raise RpcError("corrupt v2 frame: buffer table truncated")
    lens = [
        int.from_bytes(view[1 + 4 * i: 5 + 4 * i], "little")
        for i in range(nbufs)
    ]
    env_end = len(data) - sum(lens)
    if env_end < env_start:
        raise RpcError("corrupt v2 frame: buffers exceed frame length")
    bufs = []
    pos = env_end
    for n in lens:
        bufs.append(view[pos: pos + n])
        pos += n
    return pickle.loads(view[env_start:env_end], buffers=bufs)


def _decode_v3(data: bytes):
    """Decode a v3 frame body: v2 layout with a 4-byte CRC32 trailer after
    the envelope, covering every byte before it (count byte + buffer table
    + envelope). Structural impossibilities and CRC mismatches both raise
    FrameCorruptError — either way the stream cannot be resynced."""
    if len(data) < 5:
        raise FrameCorruptError("corrupt v3 frame: short body")
    nbufs = data[0]
    view = memoryview(data)
    if nbufs == 0:
        crc_off = len(data) - 4
        if zlib.crc32(view[:crc_off]) != int.from_bytes(
                view[crc_off:], "little"):
            raise FrameCorruptError("v3 frame failed CRC32 check")
        return pickle.loads(view[1:crc_off])
    env_start = 1 + 4 * nbufs
    if env_start > len(data):
        raise FrameCorruptError("corrupt v3 frame: buffer table truncated")
    lens = [
        int.from_bytes(view[1 + 4 * i: 5 + 4 * i], "little")
        for i in range(nbufs)
    ]
    crc_off = len(data) - sum(lens) - 4
    if crc_off < env_start:
        raise FrameCorruptError("corrupt v3 frame: buffers exceed frame")
    if zlib.crc32(view[:crc_off]) != int.from_bytes(
            view[crc_off: crc_off + 4], "little"):
        raise FrameCorruptError("v3 frame failed CRC32 check")
    bufs = []
    pos = crc_off + 4
    for n in lens:
        bufs.append(view[pos: pos + n])
        pos += n
    return pickle.loads(view[env_start:crc_off], buffers=bufs)


# --- receiver-side idempotency (retry dedup) ---------------------------
# A retried side-effectful request may arrive on a DIFFERENT connection
# than its first attempt (the original died — that is why it was retried),
# so dedup state is process-wide, keyed by the caller-chosen token riding
# the payload's reserved "_idem" slot. The first arrival executes; every
# duplicate awaits and re-sends the first execution's result. Bounded LRU:
# old entries age out once the window where a retry could arrive is past.
_IDEM_MAX = 4096
_idem_results: dict = {}
# Claim-order ring beside the result dict: eviction pops from the left
# instead of the old OrderedDict's move_to_end-per-hit plus a full
# list() copy + scan once past the cap — O(1) amortized per claim (the
# submit hot path pays this on every batched frame). Tokens forgotten
# via _idem_forget leave a stale ring entry behind; eviction skips it.
_idem_order: "collections.deque" = collections.deque()


def _idem_claim(token) -> tuple:
    """Returns (future, is_owner). The owner executes the handler and must
    resolve the future; non-owners await it."""
    fut = _idem_results.get(token)
    if fut is not None:
        return fut, False
    fut = asyncio.get_running_loop().create_future()
    _idem_results[token] = fut
    _idem_order.append(token)
    # Evict oldest COMPLETED entries only: an in-flight future guards an
    # active execution — evicting it would let a concurrent retry claim
    # ownership and double-execute, the exact failure this cache exists
    # to prevent. Pending entries rotate to the back; the bounded scan
    # keeps a pathological all-pending cache from spinning this loop.
    scans = 0
    while len(_idem_order) > _IDEM_MAX and scans < 8:
        scans += 1
        old = _idem_order.popleft()
        entry = _idem_results.get(old)
        if entry is None:
            continue  # forgotten: the ring entry was already stale
        if entry.done():
            del _idem_results[old]
        else:
            _idem_order.append(old)
    return fut, True


def _idem_forget(token):
    _idem_results.pop(token, None)


def _backoff_delay(attempt: int, base: float, cap: float) -> float:
    """Jittered exponential backoff: 2^(attempt-1) doubling capped at
    ``cap``, scaled by a jitter factor in [0.5, 1.0] so concurrent
    retriers (a node's workers all reconnecting after a GCS restart)
    decorrelate instead of stampeding."""
    delay = min(cap, base * (2 ** min(attempt - 1, 16)))
    return delay * (0.5 + 0.5 * random.random())


# --- runtime metrics (metrics_core.py) ---------------------------------
# Built lazily so importing rpcio stays side-effect free; per-method
# histogram/counter children are cached in plain dicts (the label lookup
# must not cost a lock + tuple sort on the send hot path).
class _RpcMetrics:
    __slots__ = ("latency", "handled", "timeouts", "retries", "bytes_out",
                 "bytes_in", "keepalive_deaths", "crc_errors",
                 "_lat", "_handled", "_timeouts", "_retries")

    def __init__(self):
        from ray_tpu._private import metrics_core as mc

        reg = mc.registry()
        self.latency = reg.histogram(
            "rpc_request_latency_seconds",
            "RPC request latency per verb, one record per ATTEMPT "
            "(a retried call records each attempt)", scale=mc.LATENCY)
        self.handled = reg.counter(
            "rpc_handled_total",
            "Requests whose handler actually EXECUTED here (idempotent "
            "replays of a deduped retry are not re-counted)")
        self.timeouts = reg.counter(
            "rpc_request_timeouts_total", "Requests that hit their deadline")
        self.retries = reg.counter(
            "rpc_retries_total", "call_with_retries re-attempts")
        self.bytes_out = reg.counter(
            "rpc_bytes_sent_total", "Frame bytes written to peers").default
        self.bytes_in = reg.counter(
            "rpc_bytes_received_total", "Frame bytes read from peers").default
        self.keepalive_deaths = reg.counter(
            "rpc_keepalive_deaths_total",
            "Connections reset after keepalive silence").default
        self.crc_errors = reg.counter(
            "rpc_frame_crc_errors_total",
            "Inbound frames failing the v3 CRC32 head check").default
        self._lat: Dict[str, Any] = {}
        self._handled: Dict[str, Any] = {}
        self._timeouts: Dict[str, Any] = {}
        self._retries: Dict[str, Any] = {}

    def lat(self, method: str):
        c = self._lat.get(method)
        if c is None:
            c = self._lat[method] = self.latency.labels(method=method)
        return c

    def handled_c(self, method: str):
        c = self._handled.get(method)
        if c is None:
            c = self._handled[method] = self.handled.labels(method=method)
        return c

    def timeout_c(self, method: str):
        c = self._timeouts.get(method)
        if c is None:
            c = self._timeouts[method] = self.timeouts.labels(method=method)
        return c

    def retry_c(self, method: str):
        c = self._retries.get(method)
        if c is None:
            c = self._retries[method] = self.retries.labels(method=method)
        return c


_MX: Optional[_RpcMetrics] = None


def _mx() -> _RpcMetrics:
    global _MX
    if _MX is None:
        _MX = _RpcMetrics()
    return _MX


# --- fault-injection write-queue markers (see faultsim.py) -------------
class _FaultMarker:
    __slots__ = ("seconds", "parts")

    def __init__(self, seconds: float = 0.0, parts: tuple = ()):
        self.seconds = seconds
        self.parts = parts


class _DelayMarker(_FaultMarker):
    pass


class _DropMarker(_FaultMarker):
    pass


class Connection:
    """One duplex peer connection. Owned by exactly one event loop."""

    _ids = itertools.count(1)

    def __init__(self, reader, writer, handler: Optional[object] = None,
                 name: str = "?", version: int = 1,
                 peer_addr: Optional[str] = None):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        # "host:port" of the remote end (faultsim partition matching and
        # diagnostics); server-side conns carry the peer's ephemeral addr
        self.peer_addr = peer_addr
        # negotiated frame format (1 = in-band pickle, 2 = out-of-band
        # buffer table, 3 = v2 + CRC head trailer); both peers agreed on
        # it during the auth preamble
        self.version = version
        # flags read once per connection: the recv/send loops are hot paths
        self._max_msg = _max_msg()
        self._oob_min = _oob_min()
        self._pending: Dict[int, asyncio.Future] = {}
        self._msg_ids = itertools.count(1)
        self._send_lock = asyncio.Lock()
        # tick-coalesced writes: frames queued in order, one flush task
        # joins small frames into a single socket write per loop tick
        self._wbuf: list = []
        self._wflush: Optional[asyncio.Task] = None
        self._closed = False
        self._close_error: Optional[Exception] = None
        self.on_close: Optional[Callable] = None
        self._recv_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._last_rx = time.monotonic()
        # wire-duplicate suppression: request msg_ids already dispatched on
        # THIS connection (a duplicated frame must not re-run its handler)
        self._seen_reqs: set = set()
        self._seen_order: collections.deque = collections.deque(maxlen=1024)
        # Arbitrary peer metadata attached at registration time.
        self.meta: Dict[str, Any] = {}

    def start(self):
        loop = asyncio.get_running_loop()
        self._recv_task = loop.create_task(self._recv_loop())
        from ray_tpu._private.config import GLOBAL_CONFIG

        # keepalive only on v3+ sessions: both ends are new enough to pong
        # (an old peer would log "no handler" warnings and never answer,
        # reading as dead). Gated off for interval <= 0.
        if self.version >= 3 and GLOBAL_CONFIG.rpc_keepalive_interval_s > 0:
            self._keepalive_task = loop.create_task(self._keepalive_loop())
        return self._recv_task

    async def _keepalive_loop(self):
        """Failure detector: ping when the connection goes quiet, declare
        the peer dead when NOTHING (ping, pong, or real traffic) has
        arrived for rpc_keepalive_timeout_s. A black-holed or hung peer is
        thereby detected in O(timeout) instead of hanging a request()
        forever (ray parity: gRPC keepalive + health checks)."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        interval = GLOBAL_CONFIG.rpc_keepalive_interval_s
        timeout = GLOBAL_CONFIG.rpc_keepalive_timeout_s
        try:
            while not self._closed:
                await asyncio.sleep(interval)
                if self._closed:
                    return
                idle = time.monotonic() - self._last_rx
                if idle > timeout:
                    logger.warning(
                        "rpc keepalive timeout on %s (%.1fs idle > %.1fs); "
                        "declaring peer dead", self.name, idle, timeout)
                    _mx().keepalive_deaths.inc()
                    await self._do_close(ConnectionLost(
                        f"keepalive timeout on {self.name}: peer silent "
                        f"for {idle:.1f}s"))
                    return
                if idle >= interval:
                    try:
                        # through the fault hook: a partition black-holes
                        # pings too (that's what makes it detectable)
                        self._enqueue_faulted(
                            "__ping",
                            self._encode_frame(0, KIND_NOTIFY, "__ping", None)
                        )
                    except Exception:
                        return
        except asyncio.CancelledError:
            raise

    def _enqueue_frame(self, parts: tuple) -> asyncio.Task:
        """Queue one frame's parts synchronously (caller order = wire
        order) and return the shared flush task."""
        self._wbuf.append(parts)
        if self._wflush is None or self._wflush.done():
            self._wflush = asyncio.get_running_loop().create_task(
                self._flush_writes()
            )
        return self._wflush

    def _encode_frame(self, msg_id: int, kind: int, method: str,
                      payload) -> tuple:
        """Encode one frame as a tuple of bytes-like parts (written to the
        socket in order, large parts by reference — no join memcpy).

        v1: one part, ``[4B len][pickle]``.
        v2: ``[4B total][1B nbufs][4B len x nbufs][envelope]`` as the head
        part, then each out-of-band buffer as its own part. The envelope is
        pickled with ``buffer_callback`` so protocol-5-aware payloads
        (numpy arrays, PickleBuffers, serialization.BufferList members)
        never enter the pickle stream.
        v3: v2 with a 4-byte CRC32 of the head (count byte + table +
        envelope) appended to the head part, before the buffers.

        Raises RpcError BEFORE anything is queued when the frame would
        exceed ``rpc_max_message_bytes`` — an oversized send must fail
        loudly at the caller, not opaquely kill the peer's recv loop.
        """
        if self.version < 2:
            data = pickle.dumps((msg_id, kind, method, payload), protocol=5)
            total = len(data)
            if total > self._max_msg:
                raise RpcError(
                    f"outgoing {method!r} message too large: {total} bytes "
                    f"> rpc_max_message_bytes={self._max_msg}"
                )
            return (total.to_bytes(_HDR, "little") + data,)
        bufs: list = []
        oob_min = self._oob_min

        def _cb(pb: pickle.PickleBuffer):
            try:
                view = pb.raw()
            except Exception:
                return True  # non-contiguous buffer: serialize in-band
            if view.nbytes < oob_min or len(bufs) >= _MAX_OOB_BUFS \
                    or view.nbytes > 0xFFFFFFFF:
                return True  # tiny / table-overflow / >4GiB: in-band
            bufs.append(view)
            return False

        env = pickle.dumps((msg_id, kind, method, payload), protocol=5,
                           buffer_callback=_cb)
        crc_len = 4 if self.version >= 3 else 0
        if not bufs:
            # control-plane common case: no table, same cost as a v1 frame
            total = 1 + len(env) + crc_len
            if total > self._max_msg:
                raise RpcError(
                    f"outgoing {method!r} message too large: {total} bytes "
                    f"> rpc_max_message_bytes={self._max_msg}"
                )
            if not crc_len:
                return (total.to_bytes(_HDR, "little") + b"\x00" + env,)
            crc = zlib.crc32(env, zlib.crc32(b"\x00"))
            return (total.to_bytes(_HDR, "little") + b"\x00" + env
                    + crc.to_bytes(4, "little"),)
        table = b"".join(v.nbytes.to_bytes(4, "little") for v in bufs)
        total = (1 + len(table) + len(env) + crc_len
                 + sum(v.nbytes for v in bufs))
        if total > self._max_msg:
            raise RpcError(
                f"outgoing {method!r} message too large: {total} bytes "
                f"({len(bufs)} out-of-band buffers) "
                f"> rpc_max_message_bytes={self._max_msg}"
            )
        nb = bytes((len(bufs),))
        head_parts = [total.to_bytes(_HDR, "little"), nb, table, env]
        if crc_len:
            # CRC over the head only: out-of-band buffers are the zero-copy
            # payload path and are excluded by design (see module docs)
            crc = zlib.crc32(env, zlib.crc32(table, zlib.crc32(nb)))
            head_parts.append(crc.to_bytes(4, "little"))
        return (b"".join(head_parts), *bufs)

    def _fault_peer(self) -> Optional[str]:
        """Identity string partition rules match against. Combines the
        socket address with the peer's REGISTERED identity (meta node_id,
        set at register_peer/register_node time) — a server-accepted conn's
        socket addr is the client's ephemeral port, which no rule can name,
        so without the registered id a partition would black-hole only the
        dialing side of a duplex connection."""
        nid = self.meta.get("node_id")
        if nid is None:
            return self.peer_addr
        if self.peer_addr is None:
            return str(nid)
        return f"{nid}|{self.peer_addr}"

    def _enqueue_faulted(self, method: str, parts: tuple):
        """Queue one frame, consulting the fault injector first. Returns
        the flush task, or None when the frame was black-holed (partition:
        the bytes vanish; deadlines/keepalive surface the loss). All fault
        actions are decided synchronously at enqueue time so frame order —
        and therefore the decision sequence per seeded rule — stays
        deterministic; delays/drops execute in-order inside the flush."""
        plan = faultsim.active_plan()
        if plan is not None:
            fault = plan.on_send(method, self._fault_peer())
            if fault is not None:
                kind, rule = fault
                faultsim.record_injection(kind, method)
                if kind == "partition":
                    return None
                if kind == "kill":
                    # rank death, not graceful exit: no flush, no atexit —
                    # the gang's supervisor must detect this, not be told
                    import signal as _signal

                    os.kill(os.getpid(), _signal.SIGKILL)
                if kind == "dup":
                    self._enqueue_frame(parts)
                elif kind == "delay":
                    self._enqueue_frame(
                        _DelayMarker((rule.param or 50.0) / 1000.0))
                elif kind == "drop":
                    return self._enqueue_frame(_DropMarker(parts=parts))
                elif kind == "corrupt":
                    head = bytearray(parts[0])
                    # flip one byte past the 4B length header (inside the
                    # CRC-covered head region), offset picked by the rule's
                    # own PRNG so the corruption site replays from the seed
                    off = _HDR + rule.rng.randrange(max(1, len(head) - _HDR))
                    head[off] ^= 0xFF
                    parts = (bytes(head),) + tuple(parts[1:])
        return self._enqueue_frame(parts)

    async def _send(self, msg_id: int, kind: int, method: str, payload):
        flush = self._enqueue_faulted(
            method, self._encode_frame(msg_id, kind, method, payload)
        )
        if flush is None:
            return  # black-holed by a partition rule
        # await the shared flush so callers keep drain() backpressure;
        # shield: one canceled sender must not kill everyone's flush
        await asyncio.shield(flush)

    def request_nowait(self, method: str, payload=None) -> asyncio.Future:
        """Enqueue a request frame SYNCHRONOUSLY and return the response
        future. Two request_nowait calls from the same tick hit the wire
        in call order — the ordered-pipelining primitive direct actor
        calls ride on (a plain ``await request()`` per call would
        serialize to one call per RTT or lose ordering across tasks)."""
        # hotpath: begin request_nowait (one frame per direct call — no
        # per-call dict copies or string formatting off the error paths)
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed",  # lint: allow-hotpath (close error path)
                                 ) from self._close_error
        msg_id = next(self._msg_ids)
        # encode before registering the future: an oversized frame raises
        # here and must not leave a pending entry behind
        parts = self._encode_frame(msg_id, KIND_REQ, method, payload)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        t0 = time.perf_counter()
        lat = _mx().lat(method)

        def _done(_f):
            self._pending.pop(msg_id, None)
            lat.record(time.perf_counter() - t0)

        fut.add_done_callback(_done)
        self._enqueue_faulted(method, parts)
        return fut
        # hotpath: end request_nowait

    async def _flush_writes(self):
        """Write every queued frame with ONE socket write per tick (frames
        stay in queue order — actor-call ordering rides on it). asyncio's
        transport issues a send syscall per write() when its buffer is
        empty, so a burst of small control frames written individually
        costs a syscall + receiver wakeup each; joined, the burst is one
        syscall and the peer's recv loop drains it in one poll."""
        # Explicit yield so the flush always runs past the currently
        # executing callback: under the loops' EAGER task factory,
        # create_task would otherwise run this body synchronously inside
        # the first _enqueue_frame and flush one-frame "bursts". Without
        # an eager factory (<=3.11) create_task already defers to the next
        # loop pass — the yield would only add a scheduling hop per burst.
        if _HAS_EAGER_FACTORY:
            await asyncio.sleep(0)
        async with self._send_lock:
            # loop until drained: frames appended while we're suspended in
            # drain() ride THIS task — a sender that sees the task not done
            # won't start another, so leaving them would stall delivery
            sent = 0
            while self._wbuf and not self._closed:
                buf, self._wbuf = self._wbuf, []
                run: list = []
                for frame in buf:
                    if isinstance(frame, _FaultMarker):
                        # injected fault tokens execute in queue order so
                        # they stall/kill the STREAM, never reorder it
                        if run:
                            self.writer.write(b"".join(run))
                            run = []
                        if isinstance(frame, _DelayMarker):
                            await self.writer.drain()
                            await asyncio.sleep(frame.seconds)
                        else:  # _DropMarker: sever mid-frame
                            head = bytes(frame.parts[0]) if frame.parts \
                                else b"\x00"
                            self.writer.write(head[:max(1, len(head) // 2)])
                            try:
                                await self.writer.drain()
                            except Exception:
                                pass
                            self._wbuf.clear()
                            await self._do_close(ConnectionLost(
                                f"fault injection dropped {self.name} "
                                f"mid-frame"))
                            return
                        continue
                    # a frame is a tuple of parts (v2 out-of-band buffers
                    # ride as separate memoryview parts, by reference)
                    for part in frame if isinstance(frame, tuple) \
                            else (frame,):
                        sent += _nbytes(part)
                        if _nbytes(part) > _JOIN_MAX:
                            # big part (object chunk / tensor): joining
                            # would memcpy MBs — flush the small run in
                            # order, then hand the view to the transport
                            if run:
                                self.writer.write(b"".join(run))
                                run = []
                            self.writer.write(part)
                        else:
                            run.append(part)
                if run:
                    self.writer.write(
                        run[0] if len(run) == 1 else b"".join(run)
                    )
                await self.writer.drain()
            if sent:
                # one counter bump per flush batch, not per frame
                _mx().bytes_out.inc(sent)

    async def request(self, method: str, payload=None, timeout: float = None,
                      idem=None) -> Any:
        """Issue one request and await its response.

        ``timeout``: seconds until RpcTimeoutError. None applies the
        ``rpc_request_timeout_s`` default — no control-plane call may hang
        forever on a silent peer; pass 0 for the rare legitimately
        unbounded wait.

        ``idem``: idempotency token for side-effectful methods. Riding the
        payload's reserved "_idem" slot, it registers the call in the
        receiver's process-wide dedup cache so a retry (possibly on a new
        connection) replays the first execution's result instead of
        double-executing the handler."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed"
                                 ) from self._close_error
        if timeout is None:
            from ray_tpu._private.config import GLOBAL_CONFIG

            timeout = GLOBAL_CONFIG.rpc_request_timeout_s
        if idem is not None:
            payload = dict(payload or {})
            payload["_idem"] = idem
        msg_id = next(self._msg_ids)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending[msg_id] = fut
        handle = None
        if timeout:
            def _expire():
                if not fut.done():
                    _mx().timeout_c(method).inc()
                    fut.set_exception(RpcTimeoutError(
                        f"request {method!r} on {self.name} exceeded "
                        f"{timeout}s deadline"))

            # call_later beats wait_for here: no wrapper task per request
            # on the hot path, just one timer handle
            handle = loop.call_later(timeout, _expire)
        t0 = time.perf_counter()
        try:
            await self._send(msg_id, KIND_REQ, method, payload)
            return await fut
        finally:
            # per-ATTEMPT latency: a retried call records every attempt
            # (including the failed ones) while the *_total counters count
            # logical executions exactly once — see _dispatch's dedup path
            _mx().lat(method).record(time.perf_counter() - t0)
            if handle is not None:
                handle.cancel()
            self._pending.pop(msg_id, None)

    async def notify(self, method: str, payload=None):
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed"
                                 ) from self._close_error
        await self._send(0, KIND_NOTIFY, method, payload)

    async def _recv_loop(self):
        error: Optional[Exception] = None
        try:
            while True:
                hdr = await self.reader.readexactly(_HDR)
                n = int.from_bytes(hdr, "little")
                if n > self._max_msg:
                    raise RpcError(f"oversized message: {n}")
                data = await self.reader.readexactly(n)
                self._last_rx = time.monotonic()
                _mx().bytes_in.inc(n + _HDR)
                if self.version >= 3:
                    msg_id, kind, method, payload = _decode_v3(data)
                elif self.version == 2:
                    # ONE read buffer per frame; payload buffers are
                    # zero-copy memoryviews into it (they keep it alive)
                    msg_id, kind, method, payload = _decode_v2(data)
                else:
                    msg_id, kind, method, payload = pickle.loads(data)
                if kind == KIND_RESP:
                    fut = self._pending.get(msg_id)
                    if fut and not fut.done():
                        fut.set_result(payload)
                elif kind == KIND_ERR:
                    fut = self._pending.get(msg_id)
                    if fut and not fut.done():
                        fut.set_exception(RpcError(payload))
                elif kind == KIND_NOTIFY and method == "__ping":
                    # answered inline (no dispatch task): the pong only
                    # proves the loop + socket are alive, which is the point
                    try:
                        self._enqueue_faulted(
                            "__pong",
                            self._encode_frame(0, KIND_NOTIFY, "__pong",
                                               None))
                    except Exception:
                        pass
                elif kind == KIND_NOTIFY and method == "__pong":
                    pass  # _last_rx above is the payload
                else:
                    if kind == KIND_REQ and msg_id:
                        # wire-duplicate suppression: a duplicated request
                        # frame (fault injection, future retransmit paths)
                        # must not re-run its handler — the first dispatch
                        # already owns sending the (single) response
                        if msg_id in self._seen_reqs:
                            logger.warning(
                                "%s: dropping duplicate request frame "
                                "%s #%d", self.name, method, msg_id)
                            continue
                        if len(self._seen_order) == self._seen_order.maxlen:
                            self._seen_reqs.discard(self._seen_order[0])
                        self._seen_order.append(msg_id)
                        self._seen_reqs.add(msg_id)
                    # spawn (strong ref): a GC'd dispatch task would drop
                    # the request without ever sending a reply
                    spawn(self._dispatch(msg_id, kind, method, payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass
        except asyncio.CancelledError:
            raise
        except FrameCorruptError as e:
            # typed, loud, and fatal for the CONNECTION only: the stream
            # offset is untrustworthy after a corrupt frame, so reset and
            # let deadlines/retries re-issue in-flight calls
            logger.warning("resetting %s: %s", self.name, e)
            _mx().crc_errors.inc()
            error = e
        except Exception as e:
            logger.exception("rpc recv loop error on %s", self.name)
            error = ConnectionLost(f"recv loop error on {self.name}: {e!r}")
        finally:
            await self._do_close(error)

    async def _dispatch(self, msg_id: int, kind: int, method: str, payload):
        task = asyncio.current_task()
        if task is not None:
            # name = the method being served: SIGUSR2 task dumps then show
            # WHICH handler a wedged dispatch is stuck in, not just that
            # one is stuck (negligible cost next to unpickle+handler)
            task.set_name(f"dispatch:{method}:{self.name}")
        handler = self.handler
        fn = getattr(handler, f"rpc_{method}", None) if handler else None
        if fn is None:
            if kind == KIND_REQ:
                await self._send(msg_id, KIND_ERR, method, f"no handler for {method!r}")
            else:
                logger.warning("%s: dropping notify %r (no handler)", self.name, method)
            return
        # Retry-level idempotency: a token claims a process-wide cache slot.
        # The first arrival executes the handler; a duplicate (a retried
        # request, possibly on a fresh connection after the original died)
        # awaits and re-sends the SAME result without re-executing.
        token = idem_fut = None
        if kind == KIND_REQ and isinstance(payload, dict):
            token = payload.pop("_idem", None)
        if token is not None:
            idem_fut, is_owner = _idem_claim(token)
            if not is_owner:
                # Replay the first execution's outcome on OUR connection.
                # An exception out of idem_fut is the CACHED EXECUTION's
                # failure (even a ConnectionLost the handler raised) — it
                # must still be answered, or the retrier stalls for its
                # whole deadline; only OUR OWN send failing is droppable.
                try:
                    result = await asyncio.shield(idem_fut)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    out = (KIND_ERR, f"{type(e).__name__}: {e}")
                else:
                    out = (KIND_RESP, result)
                try:
                    await self._send(msg_id, out[0], method, out[1])
                except (ConnectionLost, ConnectionResetError,
                        BrokenPipeError):
                    pass
                return
        release = None
        try:
            # counted HERE — after the dedup replay path has returned — so
            # a retried idempotent request counts one logical execution no
            # matter how many attempts the client's latency histogram saw
            _mx().handled_c(method).inc()
            result = fn(self, payload)
            if asyncio.iscoroutine(result):
                result = await result
            if isinstance(result, Finalized):
                release = result.release
                result = result.payload
            if idem_fut is not None and not idem_fut.done():
                idem_fut.set_result(result)
            if kind == KIND_REQ:
                await self._send(msg_id, KIND_RESP, method, result)
        except (ConnectionLost, ConnectionResetError, BrokenPipeError) as e:
            if idem_fut is not None and not idem_fut.done():
                # a FAILED execution must not be replayed to retriers —
                # evict so the retry re-executes; hand waiters the error
                _idem_forget(token)
                idem_fut.set_exception(e)
                idem_fut.add_done_callback(lambda f: f.exception())
        except Exception as e:
            logger.exception("handler %s failed on %s", method, self.name)
            if idem_fut is not None and not idem_fut.done():
                _idem_forget(token)
                idem_fut.set_exception(e)
                idem_fut.add_done_callback(lambda f: f.exception())
            if kind == KIND_REQ:
                try:
                    await self._send(msg_id, KIND_ERR, method, f"{type(e).__name__}: {e}")
                except Exception:
                    pass
        finally:
            if release is not None:
                # the response frame is past _send (handed to the
                # transport); drop our own reference to the payload so its
                # buffer views die and release() can close the resource
                # (e.g. an ObjectBuffer mmap) instead of deferring to GC
                result = None
                try:
                    release()
                except Exception:
                    logger.exception("response finalizer failed for %s", method)

    async def _do_close(self, error: Optional[Exception] = None):
        if self._closed:
            return
        self._closed = True
        self._close_error = error
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    error if error is not None
                    else ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            try:
                result = self.on_close(self)
                if asyncio.iscoroutine(result):
                    await result
            except Exception:
                logger.exception("on_close callback failed for %s", self.name)

    async def close(self):
        if self._recv_task:
            self._recv_task.cancel()
        await self._do_close()

    @property
    def closed(self):
        return self._closed


class RpcServer:
    """Asyncio TCP server; each accepted peer becomes a Connection with the
    given handler. The handler may implement ``on_connection(conn)`` /
    ``on_disconnect(conn)``."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()

    async def start(self):
        self._server = await asyncio.start_server(self._accept, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _accept(self, reader, writer):
        from ray_tpu._private.config import GLOBAL_CONFIG

        try:
            preamble = await asyncio.wait_for(
                reader.readexactly(_AUTH_LEN), GLOBAL_CONFIG.rpc_auth_timeout_s
            )
        except Exception:
            writer.close()
            return
        # run ALL digest compares unconditionally (constant-time-ish); the
        # magic picks the negotiated frame version
        token = cluster_token()
        is_v3 = hmac.compare_digest(preamble, _auth_preamble(token, 3))
        is_v2 = hmac.compare_digest(preamble, _auth_preamble(token, 2))
        is_v1 = hmac.compare_digest(preamble, _auth_preamble(token, 1))
        if not (is_v1 or is_v2 or is_v3):
            logger.warning("rejecting unauthenticated peer on :%d", self.port)
            writer.close()
            return
        version = 3 if is_v3 else (2 if is_v2 else 1)
        if version >= 2:
            # version byte after the preamble: confirms v2/v3 to the client
            # (an older server would instead have closed the connection)
            writer.write(_ACKS[version])
        peername = writer.get_extra_info("peername")
        peer_addr = f"{peername[0]}:{peername[1]}" if peername else None
        conn = Connection(reader, writer, self.handler,
                          name=f"server:{self.port}", version=version,
                          peer_addr=peer_addr)
        self.connections.add(conn)

        def _closed(c):
            self.connections.discard(c)
            cb = getattr(self.handler, "on_disconnect", None)
            if cb:
                return cb(c)

        conn.on_close = _closed
        cb = getattr(self.handler, "on_connection", None)
        if cb:
            result = cb(conn)
            if asyncio.iscoroutine(result):
                await result
        conn.start()

    async def stop(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()


async def connect(host: str, port: int, handler=None, name: str = "client",
                  retries: int = None, retry_delay: float = None,
                  token: Optional[str] = None,
                  version: Optional[int] = None,
                  total_timeout: Optional[float] = None) -> Connection:
    """``token`` overrides the ambient cluster token for THIS connection —
    the path to external services with their own credential (the remote
    KV metadata server, like Redis with requirepass).

    ``version`` pins the frame format (default: the rpc_frame_version
    flag). A v3 dial that the peer rejects — an older server closes the
    connection at the digest compare — falls back one version per redial
    (3 -> 2 -> 1), so mixed-version clusters interoperate for one release.

    Dial failures retry with EXPONENTIAL backoff + jitter: delay starts at
    ``retry_delay`` (flag: rpc_connect_retry_delay_s), doubles per attempt,
    and caps at rpc_connect_backoff_max_s — a dead peer costs attempts, not
    a connect storm. ``retries`` bounds attempts; ``total_timeout`` (used
    by GCS-outage reconnect paths) instead retries until the deadline,
    sized against gcs_client_reconnect_timeout_s."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    if retries is None:
        retries = GLOBAL_CONFIG.rpc_connect_retries
    if retry_delay is None:
        retry_delay = GLOBAL_CONFIG.rpc_connect_retry_delay_s
    cap = max(retry_delay, GLOBAL_CONFIG.rpc_connect_backoff_max_s)
    deadline = (time.monotonic() + total_timeout) if total_timeout else None
    want = min(_frame_version() if version is None else version, 3)
    addr = f"{host}:{port}"
    last = None
    attempt = 0
    while True:
        try:
            plan = faultsim.active_plan()
            if plan is not None and plan.on_connect(addr):
                faultsim.record_injection("partition", "connect")
                raise ConnectionRefusedError(
                    f"fault injection: partitioned from {addr}")
            reader, writer = await asyncio.open_connection(host, port)
            tok = cluster_token() if token is None else token
            negotiated = 1
            if want >= 2:
                writer.write(_auth_preamble(tok, want))
                await writer.drain()
                try:
                    ack = await asyncio.wait_for(
                        reader.readexactly(1),
                        GLOBAL_CONFIG.rpc_auth_timeout_s,
                    )
                except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                        ConnectionResetError, OSError) as e:
                    try:
                        writer.close()
                    except Exception:
                        pass
                    # Downgrade ONLY on a clean EOF — that is an older
                    # server deliberately closing at the unknown magic (or
                    # a token mismatch — v1 surfaces those on first use
                    # too). A reset/timeout is a transient network event;
                    # downgrading on it would silently strip CRC+keepalive
                    # from a fully capable peer for the session's lifetime.
                    msg = f"v{want} handshake refused: {e!r}"
                    if isinstance(e, asyncio.IncompleteReadError):
                        want -= 1
                    raise ConnectionRefusedError(msg) from None
                if ack != _ACKS[want]:
                    try:
                        writer.close()
                    except Exception:
                        pass
                    raise ConnectionLost(
                        f"bad version ack from {addr}: {ack!r}"
                    )
                negotiated = want
            else:
                writer.write(_auth_preamble(tok, 1))
                await writer.drain()
            conn = Connection(reader, writer, handler, name=name,
                              version=negotiated, peer_addr=addr)
            # Client-side conns get disconnect callbacks too (raylet/worker
            # GCS-reconnect loops key off this).
            cb = getattr(handler, "on_disconnect", None)
            if cb is not None:
                conn.on_close = cb
            conn.start()
            return conn
        except (ConnectionRefusedError, OSError) as e:
            last = e
            attempt += 1
            if deadline is None:
                if attempt >= retries:
                    break
            elif time.monotonic() >= deadline:
                break
            await asyncio.sleep(_backoff_delay(attempt, retry_delay, cap))
    raise ConnectionLost(f"cannot connect to {addr}: {last}")


# Transient transport failures: safe to retry (with backoff) for idempotent
# methods, and for side-effectful ones that carry an ``idem`` token.
TRANSIENT_RPC_ERRORS = (ConnectionLost, RpcTimeoutError,
                        ConnectionResetError, BrokenPipeError, OSError)


async def call_with_retries(get_conn, method: str, payload=None, *,
                            timeout: Optional[float] = None,
                            idem=None, attempts: Optional[int] = None,
                            base_delay: Optional[float] = None,
                            max_delay: Optional[float] = None):
    """Issue ``method`` with exponential backoff + jitter across transient
    transport failures (the retry/backoff classification the control plane
    rides on; ray parity: gRPC retry policies on GCS channels).

    ``get_conn``: a live Connection, or a (possibly async) zero-arg
    callable returning the CURRENT connection — reconnect loops (e.g. the
    raylet's GCS conn) swap the object out underneath, and each attempt
    re-resolves it. Returning None means "not reconnected yet": the
    attempt is charged and backed off.

    Contract: idempotent methods (heartbeats, lookups, location queries)
    may be passed bare; side-effectful ones MUST carry ``idem`` — the
    receiver dedups on it, so a retry whose original actually executed
    (response lost) replays the result instead of double-executing.
    Non-transient errors (handler failures -> RpcError) propagate on the
    first occurrence: re-running a deterministic failure is pure latency.
    """
    from ray_tpu._private.config import GLOBAL_CONFIG

    if attempts is None:
        attempts = GLOBAL_CONFIG.rpc_retry_attempts
    if base_delay is None:
        base_delay = GLOBAL_CONFIG.rpc_retry_base_delay_s
    if max_delay is None:
        max_delay = GLOBAL_CONFIG.rpc_retry_max_delay_s
    last = None
    for attempt in range(max(1, attempts)):
        if attempt:
            _mx().retry_c(method).inc()
            await asyncio.sleep(_backoff_delay(attempt, base_delay, max_delay))
        try:
            conn = get_conn() if callable(get_conn) else get_conn
            if asyncio.iscoroutine(conn):
                conn = await conn
            if conn is None or conn.closed:
                last = ConnectionLost(f"no live connection for {method!r}")
                continue
            return await conn.request(method, payload, timeout=timeout,
                                      idem=idem)
        except TRANSIENT_RPC_ERRORS as e:
            last = e
    raise last


_BG_TASKS: set = set()


def spawn(coro, name: str = None) -> asyncio.Task:
    """create_task with a STRONG reference held until completion, plus
    dropped-exception logging. The event loop keeps only weak task refs: a
    fire-and-forget task awaiting a future that is reachable only from the
    task itself forms an unrooted cycle the GC may collect mid-await —
    silently skipping the coroutine's finally blocks. (Observed in round 4:
    a collected pump task left its registry key behind and stranded every
    subsequent task of its scheduling class.) Every fire-and-forget
    create_task in system processes must go through here or an equivalent
    live structure."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    if task.done():
        # Eager task factory: the coroutine ran to completion synchronously
        # inside create_task — registering the done-callback AFTER adding to
        # _BG_TASKS would fire it immediately (discard before add) and leak
        # the entry forever. Log any exception and skip the registry.
        if not task.cancelled() and task.exception() is not None:
            logger.error("background task %s failed: %r", task.get_name(),
                         task.exception(), exc_info=task.exception())
        return task
    _BG_TASKS.add(task)

    def _done(t):
        _BG_TASKS.discard(t)
        if not t.cancelled() and t.exception() is not None:
            logger.error("background task %s failed: %r", t.get_name(),
                         t.exception(), exc_info=t.exception())

    task.add_done_callback(_done)
    return task


def enable_eager_tasks(loop: asyncio.AbstractEventLoop):
    """Python 3.12 eager task execution: a new task runs synchronously
    until its first suspension instead of paying a full loop round-trip
    before its first byte of work. For the control plane's short RPC
    dispatch handlers this removes one scheduling hop per message — the
    dominant per-op cost the BENCH_CORE analysis identified. Code that
    NEEDS deferred execution must make it explicit (``_flush_writes``
    leads with ``await asyncio.sleep(0)``)."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is not None:
        loop.set_task_factory(factory)


def _log_dropped_exception(fut) -> None:
    try:
        exc = fut.exception()
    except (asyncio.CancelledError, concurrent.futures.CancelledError):
        return
    if exc is not None:
        logger.error("fire-and-forget coroutine failed: %r", exc,
                     exc_info=exc)


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread, for sync callers.

    This is the analog of the reference's per-process io_context thread
    (ray: src/ray/common/asio/instrumented_io_context.h) embedded in a
    synchronous Python driver/worker.
    """

    def __init__(self, name: str = "rpc-io"):
        self.loop = asyncio.new_event_loop()
        enable_eager_tasks(self.loop)
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        if os.environ.get("RAY_TPU_PROFILE_DIR"):
            from ray_tpu._private.profiling import maybe_profile_thread

            maybe_profile_thread(f"ioloop-{self.thread.name}")
        self.loop.run_forever()

    def run(self, coro, timeout: float = None):
        """Run coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def submit(self, coro) -> "concurrent.futures.Future":
        """Schedule a coroutine on the loop, returning its
        ``concurrent.futures.Future`` for the caller to consume later —
        the pipelined middle ground between ``run`` (block now) and
        ``call_soon`` (never look). The chunked-collective transport
        keeps a window of these in flight so reduction of one chunk
        overlaps the RPC round trips of the next."""
        if not self.loop.is_running():
            coro.close()
            raise RuntimeError("event loop is stopped")
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def call_soon(self, coro):
        if not self.loop.is_running():
            # Shutdown race: close the coroutine (avoids the un-awaited
            # warning) but RAISE — a silent drop would hang any caller
            # blocking on a future this coroutine was meant to resolve
            # (e.g. worker._resolve_owned_missing). Fire-and-forget call
            # sites already wrap call_soon in try/except.
            coro.close()
            raise RuntimeError("event loop is stopped")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        # Fire-and-forget callers never .result() this future, and
        # run_coroutine_threadsafe swallows coroutine exceptions into it —
        # a crashed submit/registration coroutine would strand its task
        # forever with no trace. Surface the loss loudly instead.
        fut.add_done_callback(_log_dropped_exception)
        return fut

    def stop(self):
        if self.thread.is_alive() and self.loop.is_running():
            self._drain_tasks()
        # ALWAYS queue the stop + join while the thread lives: a loop that
        # has not reached run_forever yet still executes queued callbacks
        # once it starts, so this is the path that keeps an early-shutdown
        # worker from leaking a spinning io thread
        if self.thread.is_alive():
            try:
                self.loop.call_soon_threadsafe(self.loop.stop)
                self.thread.join(timeout=5)
            except Exception:
                pass

    def _drain_tasks(self):
        async def _drain():
            tasks = [t for t in asyncio.all_tasks(self.loop)
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            # let cancellations actually RUN: stopping the loop with
            # cancelled-but-unfinished tasks makes their destructors spam
            # "Task was destroyed but it is pending!" on every shutdown
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            asyncio.run_coroutine_threadsafe(_drain(), self.loop).result(
                timeout=2.0
            )
        except Exception:
            pass
