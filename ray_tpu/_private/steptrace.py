"""Step-telemetry plane: per-step trainer + collective timing records.

The observability quartet (chaos/profiling/metrics/logs) covers the
control plane; this module lights up the training data plane. Every
process keeps ONE fixed-size ring of small tuples recording

- **collective ops** (``util.collective`` allreduce/allgather/
  reducescatter/broadcast/barrier): per-group monotonic sequence number
  plus rank-local start/end/bytes — the (group, seq) key is what lets a
  GCS-side merge line up the SAME logical collective across ranks and
  attribute arrival skew to the rank that showed up last;
- **step phases** (``train.session.step_phase("data"|"h2d"|"compute"|
  "optimizer")``) and **step boundaries** (auto-delimited at
  ``session.report()``);
- **XLA compile events** (first-call / recompile timing per jitted fn,
  via ``trace_jit`` cache-size sampling and, when available, a
  ``jax.monitoring`` duration listener) so compile storms are
  attributable in the same timeline.

Metrics-core discipline applies (see metrics_core.py): ``record_*`` is
one module-global flag load + a tuple pack + a list store — no locks
(GIL-atomic enough for telemetry; a torn write loses one record, never
corrupts structure) — and the whole plane is flag-gated
(``RAY_TPU_STEPTRACE_ENABLED=0`` / cfg ``steptrace_enabled``) so it
costs nothing when off. The bench lane (BENCH_STEPTRACE_OVERHEAD=1)
gates the calibrated recorder share of a tight collective loop <2% and
asserts zero records when disabled.

Timestamps are ``time.time()`` (wall): arrival-skew comparisons happen
ACROSS processes, so the clocks must share an epoch — monotonic clocks
don't. Within one host that is exact; across hosts skew readings carry
NTP error, the same tradeoff the task-event timeline already makes.

The GCS folds per-rank records into rolling metrics via
``SkewAggregator``: per-rank ``collective_skew_seconds`` histograms
(each rank's lateness behind the first arrival) and a per-rank
``steptrace_straggler_score`` gauge (EWMA of "arrived last"), riding
the existing cluster scrape. ``merge_processes``/``chrome_trace`` build
the multi-rank timeline that ``util.state.train_timeline()``, the
dashboard Train tab, and ``ray_tpu train timeline`` export as
Chrome-trace/Perfetto JSON.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "set_enabled", "is_enabled", "record_calls", "record_collective",
    "record_phase", "record_compile", "step_mark", "phase",
    "set_train_context", "clear_train_context", "reset", "snapshot",
    "process_snapshot", "trace_jit", "install_compile_listener",
    "merge_collectives", "merge_processes", "chrome_trace",
    "SkewAggregator", "SEQ_MOD",
]

# Collective sequence numbers wrap here (32-bit): the (group, seq) join
# key stays aligned across ranks because every rank wraps at the same
# count. merge_collectives orders rows by timestamp, not seq, so a
# wrapped group still renders in arrival order.
SEQ_MOD = 1 << 32

_enabled = os.environ.get("RAY_TPU_STEPTRACE_ENABLED", "1").lower() not in (
    "0", "false", "no")
_explicit = False  # set_enabled() was called: runtime override wins
# instrumentation event count (the bench lane's calibrated-cost x count
# estimator multiplies this, same discipline as metrics_core._events)
_events = 0

_RING_DEFAULT = 8192
_ring: List[Any] = []
_ring_size = 0
_idx = 0  # monotonic per-process write index (ring slot = _idx % size)

# train-session context: stamped onto phase/step/compile records
_rank = 0
_world = 1
_step = 0
_step_start: Optional[float] = None


def _fold_cfg():
    """Fold cfg ``steptrace_enabled`` (itself env-overridable as
    ``RAY_TPU_steptrace_enabled``) into the flag — the documented kill
    switch must gate the record paths, not just the surfaces. Runs at
    import, again at first ring creation (so ``init(system_config=...)``
    overrides land), and from is_enabled(); an explicit set_enabled()
    always wins."""
    global _enabled
    if _explicit:
        return
    try:
        from ray_tpu._private.config import GLOBAL_CONFIG

        if not GLOBAL_CONFIG.steptrace_enabled:
            _enabled = False
    except Exception:
        pass


_fold_cfg()


def set_enabled(flag: bool):
    global _enabled, _explicit
    _explicit = True  # explicit call wins over the config default
    _enabled = bool(flag)


def is_enabled() -> bool:
    _fold_cfg()
    return _enabled


def record_calls() -> int:
    """Total record_* calls in this process since import (the overhead
    lane's event count)."""
    return _events


def _ensure_ring():
    global _ring, _ring_size
    if _ring_size == 0:
        _fold_cfg()  # late system_config overrides land before any write
        size = _RING_DEFAULT
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG

            size = int(GLOBAL_CONFIG.steptrace_ring_size)
        except Exception:
            pass
        _ring = [None] * max(16, size)
        _ring_size = len(_ring)
    return _ring


def reset():
    """Drop all records and counters (tests / bench phases)."""
    global _ring, _ring_size, _idx, _step, _step_start
    _ring = []
    _ring_size = 0
    _idx = 0
    _step = 0
    _step_start = None


# ---------------------------------------------------------------------------
# record paths (hot: flag load + tuple pack + list store)
# ---------------------------------------------------------------------------

def _ring_slot():
    """The live ring, or None when recording is off (first call folds
    late config overrides in before anything is written)."""
    ring = _ring
    if not ring:
        ring = _ensure_ring()
        if not _enabled:
            return None
    return ring


def record_collective(group: str, seq: int, op: str, rank: int, world: int,
                      start: float, end: float, nbytes: int,
                      wire: Optional[int] = None,
                      logical: Optional[int] = None):
    """``nbytes`` is the op's tensor payload size (unchanged series);
    ``wire`` is what this rank actually moved over the transport after
    chunk/quant encoding, and ``logical`` what the same movements would
    have cost at full precision (both default to ``nbytes`` — the
    monolithic fp32 path moves what it means). logical/wire is the
    collective backend's effective-bandwidth series (EQuARX-style int8
    quantization shows up here as ~4x)."""
    global _events, _idx
    if not _enabled:
        return
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    wire = nbytes if wire is None else wire
    ring[_idx % _ring_size] = (
        "coll", _idx, group, seq % SEQ_MOD, op, rank, world, start, end,
        nbytes, wire, wire if logical is None else logical)
    _idx += 1


def record_chunk(group: str, seq: int, chunk: int, op: str, rank: int,
                 start: float, end: float, nbytes: int):
    """One chunk of a chunked collective (transport+reduce interval for
    sub-chunk ``chunk`` of the op at (group, seq)). Chunk records render
    as their own timeline lane so overlap with compute phases is visible;
    the (group, seq) skew join deliberately ignores them — the op is
    still ONE collective row, delimited by its ``record_collective``."""
    global _events, _idx
    if not _enabled:
        return
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    ring[_idx % _ring_size] = (
        "chunk", _idx, group, seq % SEQ_MOD, chunk, op, rank, start, end,
        nbytes)
    _idx += 1


def record_phase(name: str, start: float, end: float,
                 step: Optional[int] = None, rank: Optional[int] = None):
    global _events, _idx
    if not _enabled:
        return
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    ring[_idx % _ring_size] = (
        "phase", _idx, _step if step is None else step, name,
        _rank if rank is None else rank, start, end)
    _idx += 1


def record_compile(name: str, start: float, end: float, first: bool):
    global _events, _idx
    if not _enabled:
        return
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    ring[_idx % _ring_size] = ("compile", _idx, name, bool(first), _rank,
                               start, end)
    _idx += 1


def record_restart(cause: str, start: float, end: float, generation: int):
    """One gang recovery interval (detection -> new generation ready),
    recorded by the driver-side BackendExecutor. ``cause`` is the failure
    classification (actor_died / wedged / drain / error); ``generation``
    is the gang generation that STARTED at ``end``."""
    global _events, _idx
    if not _enabled:
        return
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    ring[_idx % _ring_size] = ("restart", _idx, cause, int(generation),
                               start, end)
    _idx += 1


def _record_step(step: int, start: float, end: float):
    global _events, _idx
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    ring[_idx % _ring_size] = ("step", _idx, step, _rank, start, end)
    _idx += 1


def step_mark(now: Optional[float] = None) -> int:
    """Close the current step interval and open the next one — called by
    ``train.session.report()`` so steps auto-delimit at the natural
    reporting boundary. Returns the step index just closed."""
    global _step, _step_start
    if not _enabled:
        return _step
    now = time.time() if now is None else now
    start = _step_start if _step_start is not None else now
    closed = _step
    _record_step(closed, start, now)
    _step += 1
    _step_start = now
    return closed


def set_train_context(rank: int, world: int):
    """Adopt a train session's identity: phase/step/compile records are
    stamped with this rank until cleared."""
    global _rank, _world, _step, _step_start
    _rank = int(rank)
    _world = int(world)
    _step = 0
    _step_start = time.time()


def clear_train_context():
    global _rank, _world, _step_start
    _rank = 0
    _world = 1
    _step_start = None


class phase:
    """Context manager recording one step-phase interval. Canonical
    phases are "data", "h2d", "compute", "optimizer" (free-form strings
    are accepted — the timeline renders whatever it gets)."""

    __slots__ = ("name", "_t0")

    def __init__(self, name: str):
        self.name = name
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        record_phase(self.name, self._t0, time.time())
        return False


# ---------------------------------------------------------------------------
# compile-event hooks
# ---------------------------------------------------------------------------

def trace_jit(fn, name: Optional[str] = None):
    """Wrap a jitted callable so cache growth during a call is recorded
    as a compile event (first call vs recompile): jax compiles lazily at
    call time, so a call that grows ``fn._cache_size()`` spent its wall
    time tracing+compiling. Works on any object exposing ``_cache_size``
    (jax.jit since 0.4); silently degrades to a passthrough otherwise."""
    import functools

    label = name or getattr(fn, "__name__", None) or "jit"
    cache_size = getattr(fn, "_cache_size", None)

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if not _enabled or cache_size is None:
            return fn(*args, **kwargs)
        try:
            before = cache_size()
        except Exception:
            return fn(*args, **kwargs)
        t0 = time.time()
        out = fn(*args, **kwargs)
        try:
            after = cache_size()
        except Exception:
            return out
        if after > before:
            record_compile(label, t0, time.time(), first=(before == 0))
        return out

    return wrapped


_compile_listener_installed = False


def install_compile_listener():
    """Register a ``jax.monitoring`` duration listener mirroring backend
    compile events into the ring (global compile storms show up even for
    jitted fns nobody wrapped in ``trace_jit``). Idempotent; a missing /
    old jax degrades to a no-op."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    _compile_listener_installed = True
    try:
        from jax import monitoring
    except ImportError:
        return

    def _on_duration(event: str, duration: float, **kw):
        if _enabled and "compile" in event:
            now = time.time()
            record_compile(event.rsplit("/", 1)[-1] or event,
                           now - duration, now, first=False)

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# snapshot (the steptrace_snapshot RPC payload)
# ---------------------------------------------------------------------------

def snapshot() -> List[dict]:
    """The ring contents as dicts, oldest first. ``idx`` is the
    process-monotonic record index — consumers (SkewAggregator) use it
    to fold each record exactly once across repeated scrapes."""
    if _idx == 0:
        return []
    ring, size, idx = _ring, _ring_size, _idx
    if idx <= size:
        raw = ring[:idx]
    else:
        cut = idx % size
        raw = ring[cut:] + ring[:cut]
    out = []
    for rec in raw:
        if rec is None:  # torn slot mid-wrap: skip, never corrupt
            continue
        kind = rec[0]
        if kind == "coll":
            out.append({"kind": "coll", "idx": rec[1], "group": rec[2],
                        "seq": rec[3], "op": rec[4], "rank": rec[5],
                        "world": rec[6], "start": rec[7], "end": rec[8],
                        "bytes": rec[9],
                        "wire": rec[10] if len(rec) > 10 else rec[9],
                        "logical": rec[11] if len(rec) > 11 else rec[9]})
        elif kind == "chunk":
            out.append({"kind": "chunk", "idx": rec[1], "group": rec[2],
                        "seq": rec[3], "chunk": rec[4], "op": rec[5],
                        "rank": rec[6], "start": rec[7], "end": rec[8],
                        "bytes": rec[9]})
        elif kind == "phase":
            out.append({"kind": "phase", "idx": rec[1], "step": rec[2],
                        "phase": rec[3], "rank": rec[4], "start": rec[5],
                        "end": rec[6]})
        elif kind == "step":
            out.append({"kind": "step", "idx": rec[1], "step": rec[2],
                        "rank": rec[3], "start": rec[4], "end": rec[5]})
        elif kind == "compile":
            out.append({"kind": "compile", "idx": rec[1], "name": rec[2],
                        "first": rec[3], "rank": rec[4], "start": rec[5],
                        "end": rec[6]})
        elif kind == "restart":
            out.append({"kind": "restart", "idx": rec[1], "cause": rec[2],
                        "generation": rec[3], "start": rec[4],
                        "end": rec[5]})
    return out


def process_snapshot(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The ``steptrace_snapshot`` RPC payload: ring dump + identity."""
    out: Dict[str, Any] = {
        "pid": os.getpid(),
        "rank": _rank,
        "records": snapshot(),
        "dropped": max(0, _idx - _ring_size) if _ring_size else 0,
        "record_calls": _events,
    }
    if extra:
        out.update(extra)
    return out


# ---------------------------------------------------------------------------
# merge + skew math (GCS-side; pure functions, unit-testable)
# ---------------------------------------------------------------------------

# Arrivals to the SAME physical collective cannot be farther apart than
# the op's timeout (default collective_timeout_s=120) plus clock slop: a
# wider gap means the (group, seq) key was REUSED by a later run (groups
# reset seq to 0 on re-init, and the GCS log deliberately outlives runs).
# The join therefore clusters arrivals by time before attributing skew —
# no cross-rank coordination token needed.
JOIN_WINDOW_S = 300.0


def merge_collectives(records: Sequence[dict],
                      join_window_s: float = JOIN_WINDOW_S) -> List[dict]:
    """Join per-rank collective records by (group, seq) into arrival-skew
    rows, ordered by earliest arrival timestamp (NOT by seq: out-of-order
    delivery and seq wraparound must not scramble the timeline).

    Arrivals under one (group, seq) key are first CLUSTERED by time
    (consecutive-gap > ``join_window_s`` splits): a later training run
    that re-initialized the same group name restarts at seq 0, and its
    records must form their own rows instead of mis-joining with (or
    overwriting) the previous run's — cross-run "skew" would be minutes
    of wall clock, poisoning the straggler attribution.

    Each row: ``{group, seq, op, world, ranks: {rank: {start, end,
    bytes}}, skew, first_rank, last_rank, missing}`` where ``skew`` is
    the spread of arrival (start) times over the ranks PRESENT, the
    last/first ranks are the late/early arrivals, and ``missing`` lists
    ranks the join never saw (rank died, ring overwrote, scrape raced).
    Duplicate (group, seq, rank) records in a cluster keep the latest
    arrival."""
    by_key: Dict[tuple, List[dict]] = {}
    for rec in records:
        if rec.get("kind") != "coll":
            continue
        by_key.setdefault((rec["group"], rec["seq"] % SEQ_MOD),
                          []).append(rec)
    out = []
    for (group, seq), recs in by_key.items():
        recs.sort(key=lambda r: r["start"])
        clusters: List[List[dict]] = []
        for rec in recs:
            if clusters and \
                    rec["start"] - clusters[-1][-1]["start"] <= join_window_s:
                clusters[-1].append(rec)
            else:
                clusters.append([rec])
        for cluster in clusters:
            row = {"group": group, "seq": seq, "op": cluster[0]["op"],
                   "world": max(r.get("world", 0) for r in cluster),
                   "ranks": {}}
            for rec in cluster:  # sorted by start: newest-start wins
                row["ranks"][rec["rank"]] = {
                    "start": rec["start"], "end": rec["end"],
                    "bytes": rec.get("bytes", 0),
                    "wire": rec.get("wire", rec.get("bytes", 0)),
                    "logical": rec.get("logical", rec.get("bytes", 0)),
                }
            starts = {r: v["start"] for r, v in row["ranks"].items()}
            first_rank = min(starts, key=starts.get)
            last_rank = max(starts, key=starts.get)
            row["skew"] = starts[last_rank] - starts[first_rank]
            row["first_rank"] = first_rank
            row["last_rank"] = last_rank
            row["missing"] = sorted(
                set(range(row["world"])) - set(row["ranks"]))
            out.append(row)
    out.sort(key=lambda r: min(v["start"] for v in r["ranks"].values()))
    return out


def merge_records(records: Sequence[dict]) -> Dict[str, Any]:
    """Fold a flat record stream (already identity-stamped) into one
    merged view: collectives joined by (group, seq) with skew
    attribution; phases, steps, and compiles sorted by time."""
    colls: List[dict] = []
    phases: List[dict] = []
    steps: List[dict] = []
    compiles: List[dict] = []
    restarts: List[dict] = []
    chunks: List[dict] = []
    for rec in records:
        kind = rec.get("kind")
        if kind == "coll":
            colls.append(rec)
        elif kind == "phase":
            phases.append(rec)
        elif kind == "step":
            steps.append(rec)
        elif kind == "compile":
            compiles.append(rec)
        elif kind == "restart":
            restarts.append(rec)
        elif kind == "chunk":
            chunks.append(rec)
    phases.sort(key=lambda r: r["start"])
    steps.sort(key=lambda r: r["start"])
    compiles.sort(key=lambda r: r["start"])
    restarts.sort(key=lambda r: r["start"])
    chunks.sort(key=lambda r: r["start"])
    return {
        "collectives": merge_collectives(colls),
        "phases": phases,
        "steps": steps,
        "compiles": compiles,
        "restarts": restarts,
        "chunks": chunks,
    }


def merge_processes(processes: Sequence[dict]) -> Dict[str, Any]:
    """Fold per-process steptrace snapshots into one merged view (see
    ``merge_records``; per-record identity comes from the snapshot)."""
    flat: List[dict] = []
    for proc in processes:
        if proc.get("error"):
            continue
        ident = {"node_id": proc.get("node_id"), "pid": proc.get("pid")}
        for rec in proc.get("records", ()):
            flat.append(dict(rec, **ident))
    return merge_records(flat)


def chrome_trace(merged: Dict[str, Any]) -> List[dict]:
    """Render a merged view (``merge_processes`` output) as Chrome-trace
    JSON events — loadable in Perfetto / chrome://tracing. One process
    row per rank; step/phase/collective/compile slices on named
    threads; collective slices carry the merged skew attribution in
    ``args``."""
    trace: List[dict] = []
    seen_ranks = set()

    def proc_meta(rank):
        if rank in seen_ranks:
            return
        seen_ranks.add(rank)
        trace.append({"name": "process_name", "ph": "M", "pid": rank,
                      "args": {"name": f"rank {rank}"}})

    for rec in merged.get("steps", ()):
        proc_meta(rec["rank"])
        trace.append({
            "name": f"step {rec['step']}", "cat": "step", "ph": "X",
            "ts": rec["start"] * 1e6,
            "dur": max((rec["end"] - rec["start"]) * 1e6, 1.0),
            "pid": rec["rank"], "tid": "step",
            "args": {"step": rec["step"]},
        })
    for rec in merged.get("phases", ()):
        proc_meta(rec["rank"])
        trace.append({
            "name": rec["phase"], "cat": "phase", "ph": "X",
            "ts": rec["start"] * 1e6,
            "dur": max((rec["end"] - rec["start"]) * 1e6, 1.0),
            "pid": rec["rank"], "tid": "phases",
            "args": {"step": rec["step"]},
        })
    for row in merged.get("collectives", ()):
        for rank, v in sorted(row["ranks"].items()):
            proc_meta(rank)
            trace.append({
                "name": f"{row['op']}#{row['seq']}", "cat": "collective",
                "ph": "X", "ts": v["start"] * 1e6,
                "dur": max((v["end"] - v["start"]) * 1e6, 1.0),
                "pid": rank, "tid": f"collective:{row['group']}",
                "args": {
                    "group": row["group"], "seq": row["seq"],
                    "op": row["op"], "bytes": v.get("bytes", 0),
                    "wire": v.get("wire", v.get("bytes", 0)),
                    "skew_s": row["skew"],
                    "last_rank": row["last_rank"],
                    "arrived_last": rank == row["last_rank"],
                    "missing": row["missing"],
                },
            })
    for rec in merged.get("chunks", ()):
        proc_meta(rec["rank"])
        trace.append({
            "name": f"{rec['op']}#{rec['seq']}.{rec['chunk']}",
            "cat": "chunk", "ph": "X", "ts": rec["start"] * 1e6,
            "dur": max((rec["end"] - rec["start"]) * 1e6, 1.0),
            "pid": rec["rank"], "tid": f"chunks:{rec['group']}",
            "args": {"group": rec["group"], "seq": rec["seq"],
                     "chunk": rec["chunk"], "bytes": rec.get("bytes", 0)},
        })
    for rec in merged.get("compiles", ()):
        proc_meta(rec["rank"])
        trace.append({
            "name": rec["name"], "cat": "compile", "ph": "X",
            "ts": rec["start"] * 1e6,
            "dur": max((rec["end"] - rec["start"]) * 1e6, 1.0),
            "pid": rec["rank"], "tid": "compile",
            "args": {"first_call": bool(rec.get("first"))},
        })
    restarts = merged.get("restarts", ())
    if restarts:
        trace.append({"name": "process_name", "ph": "M", "pid": -1,
                      "args": {"name": "driver (recovery)"}})
    for rec in restarts:
        trace.append({
            "name": f"restart[{rec['cause']}] -> gen {rec['generation']}",
            "cat": "restart", "ph": "X",
            "ts": rec["start"] * 1e6,
            "dur": max((rec["end"] - rec["start"]) * 1e6, 1.0),
            "pid": -1, "tid": "recovery",
            "args": {"cause": rec["cause"],
                     "generation": rec["generation"],
                     "recovery_s": rec["end"] - rec["start"]},
        })
    return trace


class SkewAggregator:
    """GCS-side rolling skew metrics over successive cluster scrapes.

    Feeds two metric families on the host registry (they ride the
    existing /metrics cluster scrape because the GCS snapshots itself):

    - ``collective_skew_seconds{rank=}``: histogram of each rank's
      arrival lateness behind that collective's FIRST arrival (rank-
      attributable tail: a persistent straggler's histogram is visibly
      fatter at p99);
    - ``steptrace_straggler_score{rank=}``: EWMA of "this rank arrived
      last" per completed collective — 0.0 never-last .. 1.0
      always-last; ~``1/world`` is the healthy uniform value.

    Dedup across scrapes: every record carries its process-monotonic
    ``idx``; records at or below the per-(node, pid) high-water mark
    were folded already. Joins incomplete at one scrape (some ranks'
    snapshots lag) are kept pending until all ``world`` ranks arrive;
    the pending table is bounded, evicting oldest-seen incomplete joins.

    The aggregator also keeps a bounded LOG of every fresh record seen
    (identity-stamped), so the merged train timeline survives the
    processes that produced it — a trainer's final scrape (the
    BackendExecutor fires one at shutdown, before the worker gang dies)
    leaves the whole run queryable by ``ray_tpu train timeline`` /
    ``util.state.train_timeline()`` afterwards. In-memory only: a GCS
    restart starts a fresh log, same posture as the task-event buffer.
    """

    def __init__(self, registry=None, alpha: float = 0.1,
                 max_pending: int = 4096, log_limit: int = 65536,
                 join_window_s: float = JOIN_WINDOW_S):
        import threading
        from collections import deque

        from ray_tpu._private import metrics_core

        reg = registry or metrics_core.registry()
        self.log: "deque[dict]" = deque(maxlen=log_limit)
        self.join_window_s = join_window_s
        # fold() may run on executor threads (the GCS offloads the whole
        # fold+merge off its event loop): state mutates under this lock
        self._lock = threading.Lock()
        self._scrapes = 0
        self._hist = reg.histogram(
            "collective_skew_seconds",
            "per-rank collective arrival lateness behind first arrival",
            scale=metrics_core.LATENCY)
        self._gauge = reg.gauge(
            "steptrace_straggler_score",
            "EWMA of 'rank arrived last to a collective' (0..1)")
        self._folded = reg.counter(
            "steptrace_collectives_folded_total",
            "complete (group, seq) collective joins folded into skew "
            "metrics")
        self.alpha = alpha
        self.max_pending = max_pending
        # (node_id, pid) -> (max record idx folded, last scrape seen)
        self._seen: Dict[tuple, tuple] = {}
        self._pending: Dict[tuple, dict] = {}  # (group, seq) -> row
        self._scores: Dict[int, float] = {}    # rank -> EWMA

    def fold(self, processes: Sequence[dict]) -> int:
        """Ingest one cluster scrape: append every record NOT yet seen
        from its process to the log, fold the fresh collective records
        into the skew metrics. Returns how many complete collective
        joins were folded into the metrics this call. Thread-safe (the
        GCS runs it on executor threads)."""
        with self._lock:
            return self._fold_locked(processes)

    def _fold_locked(self, processes: Sequence[dict]) -> int:
        self._scrapes += 1
        fresh: List[dict] = []
        for proc in processes:
            if proc.get("error"):
                continue
            key = (proc.get("node_id"), proc.get("pid"))
            ident = {"node_id": proc.get("node_id"),
                     "pid": proc.get("pid")}
            mark, _ = self._seen.get(key, (-1, 0))
            recs = proc.get("records", ())
            # a process's top ring idx only ever grows while it lives; a
            # snapshot whose top sits BELOW the high-water mark is a NEW
            # process that recycled a dead worker's pid — start it fresh
            # instead of discarding its whole ring as already-folded
            snap_top = max((r.get("idx", 0) for r in recs), default=None)
            if snap_top is not None and snap_top < mark:
                mark = -1
            top = mark
            for rec in recs:
                idx = rec.get("idx", 0)
                if idx <= mark:
                    continue
                top = max(top, idx)
                rec = dict(rec, **ident)
                self.log.append(rec)
                if rec.get("kind") == "coll":
                    fresh.append(rec)
            self._seen[key] = (top, self._scrapes)
        # high-water marks for processes gone from many scrapes serve no
        # dedup purpose (their rings died with them) — drop them so
        # worker churn can't grow _seen without bound
        if len(self._seen) > 1024:
            floor = self._scrapes - 64
            for key in [k for k, (_, s) in self._seen.items()
                        if s < floor]:
                del self._seen[key]
        for rec in fresh:
            key = (rec["group"], rec["seq"] % SEQ_MOD)
            row = self._pending.get(key)
            if row is None:
                row = self._pending[key] = {
                    "world": rec.get("world", 0), "ranks": {},
                }
            elif row["ranks"] and rec["start"] - min(row["ranks"].values()) \
                    > self.join_window_s:
                # a (group, seq) key reused by a LATER run (groups reset
                # seq on re-init): the stale pending join can never
                # complete honestly — discard it rather than let the new
                # run's arrivals "complete" it with minutes of fake skew
                row = self._pending[key] = {
                    "world": rec.get("world", 0), "ranks": {},
                }
            elif row["ranks"] and min(row["ranks"].values()) - rec["start"] \
                    > self.join_window_s:
                continue  # stale straggler record from a previous run
            row["world"] = max(row["world"], rec.get("world", 0))
            row["ranks"][rec["rank"]] = rec["start"]
        done = 0
        for key in list(self._pending):
            row = self._pending[key]
            if row["world"] <= 0 or len(row["ranks"]) < row["world"]:
                continue
            del self._pending[key]
            done += 1
            starts = row["ranks"]
            t0 = min(starts.values())
            last = max(starts, key=starts.get)
            for rank, start in starts.items():
                self._hist.labels(rank=str(rank)).record(start - t0)
                prev = self._scores.get(rank, 0.0)
                score = prev + self.alpha * (
                    (1.0 if rank == last else 0.0) - prev)
                self._scores[rank] = score
                self._gauge.labels(rank=str(rank)).set(round(score, 6))
        if done:
            self._folded.inc(done)
        if len(self._pending) > self.max_pending:
            for key in list(self._pending)[
                    : len(self._pending) - self.max_pending]:
                del self._pending[key]
        return done

    def scores(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._scores)

    def records(self) -> List[dict]:
        """Every record the aggregator has ever accepted (bounded log,
        newest ``log_limit`` entries) — the merged-timeline source that
        outlives the worker processes."""
        with self._lock:
            return list(self.log)

    def fold_and_merge(self, processes: Sequence[dict],
                       limit: int = 0) -> Dict[str, Any]:
        """One scrape's whole CPU-bound path — fold the snapshots, copy
        the (possibly 65k-entry) log, and merge it — as a single call the
        GCS can push onto an executor thread, so none of it stalls the
        event loop. ``limit`` caps the merge to the newest N records for
        cheap polling surfaces."""
        with self._lock:
            self._fold_locked(processes)
            records = list(self.log)
            # snapshot under the lock: a concurrent fold on another
            # executor thread may be inserting a rank's first score
            scores = {str(r): s for r, s in sorted(self._scores.items())}
        if limit and len(records) > limit:
            records = records[-int(limit):]
        merged = merge_records(records)
        merged["straggler_scores"] = scores
        return merged
