"""schedsim: deterministic discrete-event scheduler simulator.

Scheduling-policy changes need reproducible evidence at a scale no CI
box can boot for real. schedsim simulates 1k-10k raylets in ONE process
under a seeded virtual clock and drives the *same* placement-scoring
code paths the live GCS runs — ``common.place_bundles`` (native engine
or Python oracle) for the baseline policy, ``topology.place_bundles_topo``
+ ``topology.plan_repack`` for the contention policy — so a policy A/B
here is an A/B of the production scorer, not of a model of it.

Determinism contract: same ``SimSpec`` (seed + chaos spec included) ->
byte-identical event trace. Nothing reads the wall clock or an unseeded
RNG; every iteration over cluster state is sorted; chaos decisions come
from each rule's OWN seeded PRNG (faultsim.FaultRule semantics).

Chaos replay reuses faultsim's rule syntax (``pattern:kind:prob:seed
[:param]``), reinterpreted for cluster-level faults — the pattern
matches simulated node ids:

    kill      (``drop``)  the node dies at a seeded time; gangs holding
                          bundles there are requeued for re-placement
    delay     (``delay``) the node's heartbeats stall ``param`` ms at a
                          seeded time: it drops out of the scheduler's
                          placement view for the window (the GCS-side
                          effect of heartbeat delay), keeping its gangs

Virtual scheduling cost: each placement attempt occupies the (serial)
scheduler for ``base + per_node * alive_nodes + per_bundle * bundles``
virtual seconds. The constants are calibrated against the live
ready->dispatch placement-latency histogram
(``raylet_task_placement_latency_seconds``, PR 6 — sub-ms attempts on
small clusters) and scale with cluster size the way the real view-scan
does; ``sched_cost_scale`` rescales them wholesale when re-calibrating
against a newer live histogram.

Reported per run: p50/p95/p99 placement latency, time-weighted cluster
utilization, aggregate ring-overlap contention (measured with the same
torus geometry for BOTH policies — the baseline ignores it when placing
but is scored by it, which is exactly the A/B), repack count, and the
sha256 of the trace (the determinism gate).
"""

from __future__ import annotations

import hashlib
import heapq
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import faultsim, topology
from ray_tpu._private.common import NodeInfo, place_bundles, res_add, res_sub
from ray_tpu._private.reqtrace import _pct  # one percentile definition

POLICIES = ("baseline", "contention")


@dataclass
class SimSpec:
    """One reproducible simulation run. Every field participates in the
    determinism contract — two equal specs produce identical traces."""

    nodes: int = 1000
    policy: str = "contention"
    seed: int = 0
    dims: Optional[Tuple[int, ...]] = None  # default: near-square 2D
    gangs: int = 0          # 0 -> nodes // 40
    gang_size: int = 8
    strategy: str = "STRICT_SPREAD"
    cpus_per_node: float = 4.0
    big_node_every: int = 16   # every Nth node gets 2x CPU (heterogeneity
                               # gives the repack pass real parking spots)
    arrival_rate: float = 50.0  # gang arrivals per virtual second
    hold_s: float = 30.0        # mean gang lifetime (exponential)
    start_delay_s: float = 1.0  # placed -> running window (bundles idle,
                                # i.e. migratable by the repack pass)
    chaos: str = ""             # faultsim rule syntax (see module doc)
    retry_s: float = 0.2        # gcs_schedule_retry_interval_s analog
    give_up_s: float = 30.0     # worker_lease_timeout analog
    # scheduler tunables SNAPSHOTTED here (not read from GLOBAL_CONFIG):
    # a trace's byte-identity must depend on the spec alone, never on
    # ambient RAY_TPU_* env of the replaying process
    max_candidates: int = 32
    repack_max_moves: int = 8
    # virtual scheduler cost model (see module docstring)
    sched_base_s: float = 200e-6
    sched_per_node_s: float = 0.05e-6
    sched_per_bundle_s: float = 50e-6
    sched_cost_scale: float = 1.0

    def n_gangs(self) -> int:
        return self.gangs or max(4, self.nodes // 40)


@dataclass
class _Gang:
    gang_id: str
    bundles: List[Dict[str, float]]
    strategy: str
    arrival_t: float
    hold_s: float
    placement: Optional[List[str]] = None
    placed_t: Optional[float] = None
    running: bool = False
    attempts: int = 0
    requeues: int = 0


class _Trace:
    __slots__ = ("lines",)

    def __init__(self):
        self.lines: List[str] = []

    def emit(self, t: float, kind: str, **kv):
        parts = [f"{t:.6f}", kind]
        parts.extend(f"{k}={kv[k]}" for k in sorted(kv))
        self.lines.append(" ".join(parts))

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"

    def sha256(self) -> str:
        return hashlib.sha256(self.text().encode()).hexdigest()


class SchedSim:
    def __init__(self, spec: SimSpec):
        if spec.policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}")
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.trace = _Trace()
        self.now = 0.0
        self._seq = 0
        self._events: list = []
        self.pending: List[_Gang] = []
        self.placed: Dict[str, _Gang] = {}
        self.sched_free_at = 0.0
        self.latencies: List[float] = []
        self.failed = 0
        self.repacks = 0
        self.contention_scores: List[float] = []
        self._rings: Dict[str, frozenset] = {}
        # nodes in an hb_delay window: invisible to NEW placement but not
        # dead — their gangs keep their capacity, departures during the
        # window still return it, and a kill landing mid-window still
        # kills (alive=False is reserved for real death)
        self._delayed: set = set()
        # utilization integral
        self._used_cpu = 0.0
        self._util_area = 0.0
        self._util_last_t = 0.0
        self._build_cluster()

    # -- cluster --------------------------------------------------------
    def _build_cluster(self):
        s = self.spec
        coords = topology.synthesize(s.nodes, s.dims)
        dims = tuple(max(c[d] for c in coords) + 1
                     for d in range(len(coords[0])))
        # cloud nodes join in arbitrary order: shuffle the id<->coord
        # assignment so node-id order (what resource-fit iterates in)
        # does not accidentally encode torus adjacency
        order = list(range(s.nodes))
        self.rng.shuffle(order)
        self.nodes: Dict[str, NodeInfo] = {}
        for i in range(s.nodes):
            cpu = s.cpus_per_node * (
                2.0 if s.big_node_every and i % s.big_node_every == 0
                else 1.0)
            nid = f"sim{i:05d}"
            c = coords[order[i]]
            self.nodes[nid] = NodeInfo(
                node_id=nid, host="sim", port=0, store_dir="",
                resources_total={"CPU": cpu},
                resources_available={"CPU": cpu},
                labels={
                    topology.COORD_LABEL: topology.format_coord(c),
                    topology.DIMS_LABEL: topology.format_coord(dims),
                },
            )
        self.total_cpu = sum(
            n.resources_total["CPU"] for n in self.nodes.values())
        self.topo = topology.Topology.from_nodes(
            sorted(self.nodes.values(), key=lambda n: n.node_id))

    # -- event plumbing -------------------------------------------------
    def _push(self, t: float, kind: str, payload=None):
        self._seq += 1
        heapq.heappush(self._events, (t, self._seq, kind, payload))

    def _advance(self, t: float):
        self._util_area += self._used_cpu * (t - self._util_last_t)
        self._util_last_t = t
        self.now = t

    def _take(self, placement: List[str], bundles):
        for nid, b in zip(placement, bundles):
            res_sub(self.nodes[nid].resources_available, b)
            self._used_cpu += sum(b.values())

    def _release(self, placement: List[str], bundles):
        for nid, b in zip(placement, bundles):
            node = self.nodes.get(nid)
            if node is not None and node.alive:
                res_add(node.resources_available, b)
            self._used_cpu -= sum(b.values())

    # -- workload + chaos -----------------------------------------------
    def _schedule_workload(self):
        s = self.spec
        t = 0.0
        for i in range(s.n_gangs()):
            t += self.rng.expovariate(s.arrival_rate)
            gang = _Gang(
                gang_id=f"g{i:04d}",
                bundles=[{"CPU": s.cpus_per_node}] * s.gang_size,
                strategy=s.strategy,
                arrival_t=t,
                hold_s=self.rng.expovariate(1.0 / s.hold_s),
            )
            self._push(t, "arrive", gang)
        horizon = t + s.give_up_s
        for rule in faultsim.parse_spec(s.chaos):
            if rule.kind not in ("drop", "delay"):
                continue
            for nid in sorted(self.nodes):
                if not rule.fires(nid):
                    continue  # PRNG advances only on regex matches
                at = rule.rng.uniform(0.0, horizon)
                if rule.kind == "drop":
                    self._push(at, "kill", nid)
                else:
                    dur = (rule.param or 50.0) / 1e3
                    self._push(at, "hb_delay", (nid, dur))

    # -- placement ------------------------------------------------------
    def _attempt_cost(self, n_alive: int, n_bundles: int) -> float:
        s = self.spec
        return s.sched_cost_scale * (
            s.sched_base_s + s.sched_per_node_s * n_alive
            + s.sched_per_bundle_s * n_bundles)

    def _idle_bundles(self) -> list:
        """Placed-but-not-yet-running gangs' bundles (the sim analog of
        reservations nothing consumes yet) — what plan_repack may move."""
        rows = []
        for gid in sorted(self.placed):
            g = self.placed[gid]
            if g.running or g.placement is None:
                continue
            for idx, nid in enumerate(g.placement):
                rows.append((gid, idx, nid, dict(g.bundles[idx])))
        return rows

    def _try_place(self, gang: _Gang):
        s = self.spec
        alive = [self.nodes[nid] for nid in sorted(self.nodes)
                 if self.nodes[nid].alive and nid not in self._delayed]
        gang.attempts += 1
        cost = self._attempt_cost(len(alive), len(gang.bundles))
        done_at = max(self.now, self.sched_free_at) + cost
        self.sched_free_at = done_at

        moves: list = []
        if s.policy == "contention":
            # same dispatch point the GCS uses: the common.place_bundles
            # wrapper with a topology takes the contention scorer
            placement = place_bundles(
                alive, gang.bundles, gang.strategy,
                topology=self.topo, committed_rings=self._rings,
                max_candidates=s.max_candidates)
            if placement is None and gang.strategy == "STRICT_SPREAD":
                plan = topology.plan_repack(
                    alive, gang.bundles, gang.strategy,
                    self._idle_bundles(), max_moves=s.repack_max_moves)
                if plan is not None:
                    placement, moves = plan
        else:
            placement = place_bundles(alive, gang.bundles, gang.strategy)

        if placement is None:
            if done_at - gang.arrival_t + s.retry_s > s.give_up_s:
                self.failed += 1
                self.trace.emit(done_at, "infeasible", gang=gang.gang_id,
                                attempts=gang.attempts)
            else:
                self._push(done_at + s.retry_s, "retry", gang)
            return

        for mv in moves:
            moved = self.placed.get(mv.pg_id)
            if moved is None or moved.placement is None:
                continue
            b = moved.bundles[mv.bundle_index]
            src = self.nodes.get(mv.from_node)
            if src is not None and src.alive:
                res_add(src.resources_available, b)
            res_sub(self.nodes[mv.to_node].resources_available, b)
            moved.placement[mv.bundle_index] = mv.to_node
            self._rings[mv.pg_id] = self.topo.ring_links(moved.placement)
            self.repacks += 1
            self.trace.emit(done_at, "repack", gang=mv.pg_id,
                            bundle=mv.bundle_index,
                            src=mv.from_node, dst=mv.to_node)

        self._take(placement, gang.bundles)
        gang.placement = list(placement)
        gang.placed_t = done_at
        self.placed[gang.gang_id] = gang
        self.latencies.append(done_at - gang.arrival_t)
        ring = self.topo.ring_links(placement)
        # scored with the same geometry under BOTH policies — baseline
        # ignores contention when placing but is measured by it (the A/B)
        score = self.topo.score(placement, self._rings)
        self._rings[gang.gang_id] = ring
        self.contention_scores.append(float(score.contention))
        self.trace.emit(
            done_at, "place", gang=gang.gang_id,
            attempts=gang.attempts, contention=f"{score.contention:g}",
            compact=f"{score.compactness:.3f}",
            nodes=",".join(placement),
        )
        # epoch-stamped: a gang requeued by chaos gets fresh start/depart
        # events; stale ones from the pre-requeue placement must not fire
        self._push(done_at + self.spec.start_delay_s, "start",
                   (gang, gang.requeues))
        self._push(done_at + gang.hold_s, "depart", (gang, gang.requeues))

    # -- event handlers -------------------------------------------------
    def _on_kill(self, nid: str):
        node = self.nodes.get(nid)
        if node is None or not node.alive:
            return
        node.alive = False
        self.trace.emit(self.now, "kill", node=nid)
        for gid in sorted(self.placed):
            g = self.placed[gid]
            if g.placement and nid in g.placement:
                self._release(g.placement, g.bundles)
                self._rings.pop(gid, None)
                del self.placed[gid]
                g.placement = None
                g.placed_t = None
                g.running = False
                g.requeues += 1
                g.arrival_t = self.now  # latency restarts at requeue
                self.trace.emit(self.now, "requeue", gang=gid,
                                reason=f"node_death:{nid}")
                self._push(self.now, "retry", g)

    def _on_hb_delay(self, nid: str, dur: float):
        node = self.nodes.get(nid)
        if node is None or not node.alive or nid in self._delayed:
            return
        self._delayed.add(nid)  # out of the placement view for the window
        self.trace.emit(self.now, "hb_delay", node=nid,
                        ms=f"{dur * 1e3:.0f}")
        self._push(self.now + dur, "hb_restore", nid)

    def _on_hb_restore(self, nid: str):
        if nid in self._delayed:
            self._delayed.discard(nid)
            self.trace.emit(self.now, "hb_restore", node=nid)

    # -- main loop ------------------------------------------------------
    def run(self) -> dict:
        self._schedule_workload()
        while self._events:
            t, _, kind, payload = heapq.heappop(self._events)
            self._advance(t)
            if kind == "arrive":
                self.trace.emit(t, "arrive", gang=payload.gang_id,
                                size=len(payload.bundles),
                                strategy=payload.strategy)
                self._try_place(payload)
            elif kind == "retry":
                if payload.placement is None:
                    self._try_place(payload)
            elif kind == "start":
                gang, epoch = payload
                if gang.gang_id in self.placed and epoch == gang.requeues:
                    gang.running = True
            elif kind == "depart":
                gang, epoch = payload
                if gang.gang_id in self.placed and epoch == gang.requeues:
                    self._release(gang.placement, gang.bundles)
                    self._rings.pop(gang.gang_id, None)
                    del self.placed[gang.gang_id]
                    self.trace.emit(t, "depart", gang=gang.gang_id)
            elif kind == "kill":
                self._on_kill(payload)
            elif kind == "hb_delay":
                self._on_hb_delay(*payload)
            elif kind == "hb_restore":
                self._on_hb_restore(payload)
        return self.report()

    # -- reporting ------------------------------------------------------
    def report(self) -> dict:
        lat = sorted(self.latencies)
        mean_cont = (sum(self.contention_scores)
                     / len(self.contention_scores)
                     if self.contention_scores else 0.0)
        return {
            "policy": self.spec.policy,
            "nodes": self.spec.nodes,
            "gangs": self.spec.n_gangs(),
            "placed": len(self.latencies),
            "failed": self.failed,
            "repacks": self.repacks,
            "placement_latency_s": {
                "p50": _pct(lat, 0.50),
                "p95": _pct(lat, 0.95),
                "p99": _pct(lat, 0.99),
                "max": lat[-1] if lat else 0.0,
            },
            "utilization": (
                self._util_area / (self.total_cpu * self._util_last_t)
                if self._util_last_t > 0 else 0.0),
            "mean_contention": mean_cont,
            "total_contention": sum(self.contention_scores),
            "final_ring_overlap_ratio": self.topo.overlap_ratio(
                self._rings),
            "events": len(self.trace.lines),
            "trace_sha256": self.trace.sha256(),
        }


def run(spec: SimSpec) -> dict:
    """Run one simulation; returns the report dict (see SchedSim.report).
    Attach the trace via ``run_with_trace`` when replay/diffing matters."""
    return SchedSim(spec).run()


def run_with_trace(spec: SimSpec) -> Tuple[dict, str]:
    sim = SchedSim(spec)
    report = sim.run()
    return report, sim.trace.text()
