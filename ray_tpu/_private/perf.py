"""Core-API microbenchmarks (``ray_tpu microbenchmark``).

Reference parity: ray python/ray/_private/ray_perf.py:93-311 (`ray
microbenchmark`) — the standard suite of control-plane throughput numbers:
task submission (sync/async), actor calls (1:1 and async), put/get of
small objects, and put gigabytes. Values are machine-dependent; the suite
exists so scheduler/runtime regressions show up as numbers, not vibes.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

import numpy as np


def _timeit(name: str, fn: Callable[[], int], warmup: int = 1,
            repeat: int = 3) -> Tuple[str, float]:
    """fn runs one batch and returns how many operations it performed;
    report the best ops/s across repeats (like ray_perf's timeit)."""
    for _ in range(warmup):
        fn()
    best = 0.0
    for _ in range(repeat):
        t0 = time.perf_counter()
        n = fn()
        dt = time.perf_counter() - t0
        best = max(best, n / dt)
    return name, best


def _lat_hist():
    """Standalone log2 latency histogram (metrics_core) for per-op tail
    tracking: the sequential benches time EACH op into it so BENCH_CORE
    carries p50/p95/p99, not just the mean ops/s (tail regressions — a
    stalled dispatch pass, a GC pause per N ops — are invisible in
    means). Batched/pipelined benches keep mean-only: a per-op latency
    inside a 1000-deep pipeline measures queue depth, not the runtime."""
    from ray_tpu._private import metrics_core as mc

    return mc.Histogram({}, scale=mc.LATENCY)


def _lat_summary(h) -> dict:
    from ray_tpu._private import metrics_core as mc

    qs = mc.hist_quantiles(h._series(), (0.5, 0.95, 0.99))
    return {"p50_us": round(qs[0.5] * 1e6, 1),
            "p95_us": round(qs[0.95] * 1e6, 1),
            "p99_us": round(qs[0.99] * 1e6, 1)}


def run_object_plane_bench(small: bool = False) -> List[dict]:
    """Dedicated object-plane lane: put / get latency at 100B, 64KB, 1MB
    and 64MB (8MB in --small/CI mode) with p50/p95/p99 via the
    metrics_core histogram path. 100B rides the inline memory store by
    design; the bulk sizes must be slab-backed (arena data path) — each
    row carries ``slab_backed`` so CI can gate the structural invariant,
    not just the throughput."""
    import ray_tpu  # noqa: F401 (cluster must already be initialized)
    from ray_tpu._private.worker import global_worker

    cw = global_worker.core_worker
    big = ("8MB", 8 << 20, 6) if small else ("64MB", 64 << 20, 8)
    sizes = [
        ("100B", 100, 200 if small else 1000),
        ("64KB", 64 * 1024, 100 if small else 400),
        ("1MB", 1 << 20, 30 if small else 100),
        big,
    ]
    results: List[dict] = []
    for name, size, iters in sizes:
        arr = np.arange(size, dtype=np.uint8)
        hput, hget = _lat_hist(), _lat_hist()
        slab_backed = False
        put_s = get_s = 0.0
        # one warmup op (slab lease, worker pools) outside the clocks
        ray_tpu.get(ray_tpu.put(arr))
        for _ in range(iters):
            t0 = time.perf_counter()
            ref = ray_tpu.put(arr)
            t1 = time.perf_counter()
            got = ray_tpu.get(ref)
            t2 = time.perf_counter()
            hput.record(t1 - t0)
            hget.record(t2 - t1)
            put_s += t1 - t0
            get_s += t2 - t1
            buf = cw._pinned_buffers.get(ref.binary())
            if buf is not None and getattr(buf, "seg_id", None) is not None:
                slab_backed = True
            assert got.nbytes == size
            del ref, got, buf
        for op, h, secs in (("put", hput, put_s), ("get", hget, get_s)):
            row = {"benchmark": f"obj {op} {name}",
                   "value": round(iters / secs, 1) if secs else 0.0,
                   "unit": "ops/s", "bytes": size,
                   "slab_backed": slab_backed}
            row.update(_lat_summary(h))
            results.append(row)
            print(f"obj {op} {name:<6s} {row['value']:>12,.1f} ops/s  "  # lint: allow-print
                  f"p50={row['p50_us']:,.0f}us p95={row['p95_us']:,.0f}us "
                  f"p99={row['p99_us']:,.0f}us slab={slab_backed}")
    return results


def run_transfer_plane_bench(small: bool = False) -> List[dict]:
    """Cross-node transfer lane (arena-to-arena plane): push and pull
    MB/s at 128KB / 1MB / 64MB (8MB in --small/CI mode) between two
    live nodes — 128KB, not 64KB, because anything at or under the
    100KB inline threshold rides task specs / the owner's memory store
    and never touches the transfer plane — p50/p95/p99 per op, plus
    the structural invariant rows ride
    on: on a slab-backed store every cross-node ``fetch`` / ``push_rx``
    flow row must report ``path="arena"`` (receive-side slab assembly —
    heap rows mean the copy path silently came back). Requires an
    initialized cluster with >= 2 alive nodes; each round moves a FRESH
    object so the push dedup / local-copy short-circuits never hide the
    transfer."""
    import ray_tpu
    from ray_tpu.util import state
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )
    from ray_tpu.util.transfer import push_object

    me = ray_tpu.get_runtime_context().get_node_id()
    peers = [n["node_id"] for n in ray_tpu.nodes()
             if n["alive"] and n["node_id"] != me]
    if not peers:
        raise RuntimeError(
            "run_transfer_plane_bench needs a second alive node"
        )
    peer = peers[0]

    @ray_tpu.remote
    def _fetch(r):
        return r.nbytes

    big = ("8MB", 8 << 20, 4) if small else ("64MB", 64 << 20, 6)
    sizes = [
        # smallest store-backed size: anything <= the 100KB inline
        # threshold rides the owner's memory store / task specs and
        # never touches the transfer plane at all
        ("128KB", 128 * 1024, 10 if small else 30),
        ("1MB", 1 << 20, 8 if small else 20),
        big,
    ]
    results: List[dict] = []
    for name, size, iters in sizes:
        for op in ("push", "pull"):
            h = _lat_hist()
            best = 0.0
            for i in range(iters):
                arr = np.full(size, (i * 7 + len(name)) % 251, np.uint8)
                ref = ray_tpu.put(arr)
                t0 = time.perf_counter()
                if op == "push":
                    ok = push_object(ref, [peer]) == 1
                else:
                    ok = ray_tpu.get(_fetch.options(
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            peer)
                    ).remote(ref), timeout=120) == size
                dt = time.perf_counter() - t0
                assert ok, (op, name, i)
                h.record(dt)
                best = max(best, size / dt / 1e6)
                del ref
            row = {"benchmark": f"xfer {op} {name}", "value": round(best, 2),
                   "unit": "MB/s", "bytes": size}
            row.update(_lat_summary(h))
            results.append(row)
    # structural invariant: the flow log's receive rows name their path
    time.sleep(0.5)  # let the last push_rx row land in the remote ring
    flows = state.object_summary().get("flows") or []
    rx = [f for f in flows if f.get("kind") in ("fetch", "push_rx")]
    arena_paths = bool(rx) and all(f.get("path") == "arena" for f in rx)
    from ray_tpu._private.worker import global_worker

    slab = bool(getattr(global_worker.core_worker, "arena_enabled", False))
    for row in results:
        row["arena_paths"] = arena_paths
        row["slab_backed"] = slab
        print(f"{row['benchmark']:<16s} {row['value']:>10,.1f} MB/s  "  # lint: allow-print
              f"p50={row['p50_us']:,.0f}us p95={row['p95_us']:,.0f}us "
              f"p99={row['p99_us']:,.0f}us arena={arena_paths}")
    return results


def run_microbenchmarks(select: str = "", small: bool = False) -> List[dict]:
    """Run the suite against an initialized ray_tpu cluster. ``select``
    substring-filters benchmark names; ``small`` shrinks batch sizes (CI)."""
    import ray_tpu

    results: List[dict] = []
    batch = 100 if small else 1000
    data_mb = 10 if small else 100

    @ray_tpu.remote
    def nop(*_a):
        return b"ok"

    @ray_tpu.remote
    class Sink:
        def ping(self, *_a):
            return b"ok"

        async def aping(self):
            return b"ok"

    def record(name, ops_s, unit="ops/s", lat=None):
        row = {"benchmark": name, "value": round(ops_s, 1), "unit": unit}
        tail = ""
        if lat is not None and lat.count():
            row.update(_lat_summary(lat))
            tail = (f"  p50={row['p50_us']:,.0f}us "
                    f"p95={row['p95_us']:,.0f}us "
                    f"p99={row['p99_us']:,.0f}us")
        results.append(row)
        # CLI table output (ray_tpu microbenchmark prints to stdout)
        print(f"{name:<42s} {ops_s:>12,.1f} {unit}{tail}")  # lint: allow-print

    benches: Dict[str, Tuple[str, Callable[[], Tuple[str, float]]]] = {}

    def bench(key, display):
        def deco(fn):
            benches[key] = (display, fn)
            return fn
        return deco

    @bench("single_client_tasks_sync", "single client tasks sync")
    def _tasks_sync():
        h = _lat_hist()

        def run():
            for _ in range(batch // 10):
                t0 = time.perf_counter()
                ray_tpu.get(nop.remote())
                h.record(time.perf_counter() - t0)
            return batch // 10
        return _timeit("single client tasks sync", run) + (h,)

    @bench("single_client_tasks_async", "single client tasks async")
    def _tasks_async():
        def run():
            ray_tpu.get([nop.remote() for _ in range(batch)])
            return batch
        return _timeit("single client tasks async", run)

    @bench("actor_calls_sync_1_1", "1:1 actor calls sync")
    def _actor_sync():
        a = Sink.remote()
        ray_tpu.get(a.ping.remote())
        h = _lat_hist()

        def run():
            for _ in range(batch // 10):
                t0 = time.perf_counter()
                ray_tpu.get(a.ping.remote())
                h.record(time.perf_counter() - t0)
            return batch // 10
        out = _timeit("1:1 actor calls sync", run)
        ray_tpu.kill(a)
        return out + (h,)

    @bench("actor_calls_async_1_1", "1:1 actor calls async")
    def _actor_async():
        a = Sink.remote()
        ray_tpu.get(a.ping.remote())

        def run():
            ray_tpu.get([a.ping.remote() for _ in range(batch)])
            return batch
        out = _timeit("1:1 actor calls async", run)
        ray_tpu.kill(a)
        return out

    @bench("actor_calls_async_n_n", "n:n actor calls async")
    def _actor_nn():
        # 4 actors fed concurrently from this client (ray_perf's n:n shape
        # with the caller side folded into one submitting process)
        actors = [Sink.remote() for _ in range(4)]
        ray_tpu.get([a.ping.remote() for a in actors])

        def run():
            refs = []
            for a in actors:
                refs.extend(a.ping.remote() for _ in range(batch // 4))
            ray_tpu.get(refs)
            return (batch // 4) * 4
        out = _timeit("n:n actor calls async", run)
        for a in actors:
            ray_tpu.kill(a)
        return out

    @bench("get_10k_refs", "get 10k small refs")
    def _get_10k():
        n = 1000 if small else 10000
        refs = [ray_tpu.put(b"x" * 100) for _ in range(n)]

        def run():
            got = ray_tpu.get(refs)
            assert len(got) == n
            return n
        return _timeit("get 10k small refs", run)

    @bench("put_small", "small put (100B)")
    def _put_small():
        h = _lat_hist()

        def run():
            for _ in range(batch):
                t0 = time.perf_counter()
                ray_tpu.put(b"x" * 100)
                h.record(time.perf_counter() - t0)
            return batch
        return _timeit("small put (100B)", run) + (h,)

    @bench("put_get_roundtrip", "put+get roundtrip (1KB)")
    def _put_get():
        h = _lat_hist()

        def run():
            for _ in range(batch // 10):
                t0 = time.perf_counter()
                ray_tpu.get(ray_tpu.put(b"x" * 1000))
                h.record(time.perf_counter() - t0)
            return batch // 10
        return _timeit("put+get roundtrip (1KB)", run) + (h,)

    @bench("put_get_1mb_numpy", "put+get 1MB numpy")
    def _put_get_1mb():
        # the zero-copy object-plane latency number: serialize (out-of-band
        # views) -> shm write -> register -> mmap read -> deserialize
        arr = np.arange(1024 * 1024, dtype=np.uint8)
        n = max(1, batch // 10)
        h = _lat_hist()

        def run():
            got = None
            for _ in range(n):
                t0 = time.perf_counter()
                got = ray_tpu.get(ray_tpu.put(arr))
                h.record(time.perf_counter() - t0)
            assert got.nbytes == arr.nbytes
            del got
            return n
        return _timeit("put+get 1MB numpy", run) + (h,)

    @bench("actor_call_1mb_arg", "actor call 1MB arg")
    def _actor_1mb_arg():
        # bulk-argument path: the arg exceeds the inline threshold, so each
        # call ships it through the object plane and the worker maps it
        arr = np.arange(1024 * 1024, dtype=np.uint8)
        a = Sink.remote()
        ray_tpu.get(a.ping.remote())
        n = max(1, batch // 10)

        def run():
            ray_tpu.get([a.ping.remote(arr) for _ in range(n)])
            return n
        out = _timeit("actor call 1MB arg", run)
        ray_tpu.kill(a)
        return out

    @bench("actor_call_64kb_arg", "actor call 64KB arg")
    def _actor_64kb_arg():
        # inline-argument path: below the inline threshold the arg rides the
        # rpc frame itself — out-of-band on v2, so the array is never copied
        # into the pickle stream on send
        arr = np.arange(64 * 1024, dtype=np.uint8)
        a = Sink.remote()
        ray_tpu.get(a.ping.remote())
        n = max(1, batch // 4)

        def run():
            ray_tpu.get([a.ping.remote(arr) for _ in range(n)])
            return n
        out = _timeit("actor call 64KB arg", run)
        ray_tpu.kill(a)
        return out

    @bench("put_gigabytes", "put gigabytes")
    def _put_gb():
        arr = np.zeros(data_mb * 1024 * 1024, dtype=np.uint8)

        def run():
            ref = ray_tpu.put(arr)
            got = ray_tpu.get(ref)
            assert got.nbytes == arr.nbytes
            del ref, got
            return 2 * arr.nbytes  # bytes moved (put + get)
        # warmup=3: the first cycles write fresh tmpfs pages and seed the
        # store's recycling pool; steady-state puts then memcpy into warm
        # pages — the regime a training loop's put/free cadence lives in
        name, bps = _timeit("put gigabytes", run, warmup=3, repeat=3)
        return name, bps / 1e9  # GB/s

    import gc

    for key, (display, fn) in benches.items():
        # match either the registry key or the printed display name
        if select and select not in key and select not in display:
            continue
        # isolate: collect the previous bench's dropped refs and let the
        # resulting free bursts drain before timing the next bench (the
        # 10k-refs teardown otherwise bleeds into put bandwidth)
        gc.collect()
        time.sleep(0.5)
        out = fn()
        name, value = out[0], out[1]
        lat = out[2] if len(out) > 2 else None
        record(name, value, "GB/s" if key == "put_gigabytes" else "ops/s",
               lat=lat)
    if not results:
        print(f"no benchmarks matched --select {select!r}; available: "  # lint: allow-print
              + ", ".join(benches))
    return results


# stage rows for the control-plane lane: display label -> (metric, label
# filter). Remaining labels (node, path, ...) are merged — the lane reports
# the cluster-wide distribution per stage, not per-node shards.
_CP_STAGES = (
    ("id mint", "control_plane_stage_seconds", {"stage": "id_mint"}),
    ("envelope build", "control_plane_stage_seconds",
     {"stage": "envelope_build"}),
    ("submit rpc", "rpc_request_latency_seconds", {"method": "submit_batch"}),
    ("lease wait", "rpc_request_latency_seconds",
     {"method": "lease_workers"}),
    ("dispatch (placement)", "raylet_task_placement_latency_seconds", None),
    ("dispatch (execute rpc)", "rpc_request_latency_seconds",
     {"method": "execute_task"}),
    ("dispatch (batch rpc)", "rpc_request_latency_seconds",
     {"method": "execute_task_batch"}),
    ("submit->run", "control_plane_stage_seconds",
     {"stage": "submit_to_run"}),
    ("result return", "control_plane_stage_seconds",
     {"stage": "result_return"}),
)


def run_control_plane_bench(small: bool = False) -> List[dict]:
    """Control-plane lane (``BENCH_CONTROL_PLANE=1``): run the two
    sync-roundtrip microbenchmarks (the rows the fast-path levers target),
    then scrape the cluster-wide metrics snapshot and report the per-stage
    latency breakdown of one call — envelope build, id mint, submit RPC,
    lease wait, dispatch, result return — from the metrics-core histograms
    every process already records. Requires
    ``RAY_TPU_control_plane_stage_timing=1`` exported BEFORE init so the
    driver, raylet and workers all inherit the stage clocks."""
    from ray_tpu._private import metrics_core as mc
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg

    if not cfg.control_plane_stage_timing:
        raise RuntimeError(
            "control-plane bench needs RAY_TPU_control_plane_stage_timing=1 "
            "in the environment before ray_tpu.init() — otherwise the stage "
            "histograms this lane reads are never recorded")

    rows: List[dict] = []
    # substring select would also catch the *_async rows ("async" contains
    # "sync"), so filter by exact registry key, one bench per pass
    for sel in ("single_client_tasks_sync", "actor_calls_sync_1_1"):
        rows.extend(run_microbenchmarks(select=sel, small=small))

    from ray_tpu.util.metrics import cluster_snapshot

    snap = cluster_snapshot().get("merged", {})

    def stage_series(metric: str, want) -> dict:
        """One mergeable series for (metric, label filter): series whose
        tags match ``want`` are folded together across their remaining
        labels (node, path, ...)."""
        acc: dict = {}
        for s in (snap.get(metric) or {}).get("series", ()):
            tags = s.get("tags", {})
            if want and any(tags.get(k) != v for k, v in want.items()):
                continue
            if not acc:
                acc = {"buckets": list(s.get("buckets", ())),
                       "boundaries": list(s.get("boundaries", ())),
                       "count": s.get("count", 0),
                       "sum": s.get("sum", 0.0)}
            elif acc["boundaries"] == list(s.get("boundaries", ())):
                acc["buckets"] = [a + b for a, b in
                                  zip(acc["buckets"], s.get("buckets", ()))]
                acc["count"] += s.get("count", 0)
                acc["sum"] += s.get("sum", 0.0)
        return acc

    print(f"{'stage':<24s} {'calls':>8s} {'mean_us':>10s} "  # lint: allow-print
          f"{'p50_us':>10s} {'p95_us':>10s} {'p99_us':>10s}")
    for label, metric, want in _CP_STAGES:
        s = stage_series(metric, want)
        count = int(s.get("count", 0) or 0)
        row = {"benchmark": f"cp stage {label}", "value": count,
               "unit": "calls"}
        if count:
            qs = mc.hist_quantiles(s, (0.5, 0.95, 0.99))
            row.update({"mean_us": round(s["sum"] / count * 1e6, 1),
                        "p50_us": round(qs[0.5] * 1e6, 1),
                        "p95_us": round(qs[0.95] * 1e6, 1),
                        "p99_us": round(qs[0.99] * 1e6, 1)})
            print(f"{label:<24s} {count:>8d} {row['mean_us']:>10,.1f} "  # lint: allow-print
                  f"{row['p50_us']:>10,.1f} {row['p95_us']:>10,.1f} "
                  f"{row['p99_us']:>10,.1f}")
        else:
            row["note"] = "no samples"
            print(f"{label:<24s} {0:>8d}        (no samples)")  # lint: allow-print
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# Collective backend lane (BENCH_COLLECTIVE=1)
# ---------------------------------------------------------------------------


def run_collective_bench(small: bool = False) -> List[dict]:
    """Collective-backend lane: store-path allreduce latency at
    64KB / 1MB / 64MB x {fp32, int8} x world {2, 4} with p50/p95/p99,
    the chunked-vs-monolithic A/B at the top size (the tentpole gate:
    chunked must not lose, target >=1.3x), the int8 wire-compression
    ratio (logical/wire bytes, target >=2x) with a driver-side check
    that the quantized result stays inside the analytic per-block error
    bound, and the skewed-rank sub-lane: one rank's kv_put RPCs are
    slowed through the faultsim machinery and straggler-aware chunk
    ordering (EWMA-reordered fetch schedule) is A/B'd against FIFO.
    ``small`` drops the 64MB size and shrinks iteration counts (CI)."""
    import ray_tpu
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg

    sizes = [(64 << 10, "64KB", 30), (1 << 20, "1MB", 12),
             (64 << 20, "64MB", 3)]
    if small:
        sizes = [(64 << 10, "64KB", 10), (1 << 20, "1MB", 5)]
    worlds = [2, 4]
    rows: List[dict] = []

    @ray_tpu.remote
    class ColWorker:
        def _rt_init_collective(self, world_size, rank, backend, group_name,
                                epoch=0, quant=""):
            from ray_tpu.util import collective as col

            col.init_collective_group(world_size, rank, backend, group_name,
                                      epoch=epoch, quant=quant)
            return rank

        def set_cfg(self, updates):
            from ray_tpu._private.config import GLOBAL_CONFIG

            GLOBAL_CONFIG.update(updates)
            return True

        def run_allreduce(self, group, nbytes, iters, seed, op="sum",
                          return_out=False, nudge=False):
            """Time ``iters`` allreduces of an nbytes fp32 tensor; returns
            per-op durations plus this process's wire/logical byte and
            chunk-retry deltas (from the collective transport counters).
            ``nudge`` issues a throwaway kv_del before each op — the hook
            the skew sub-lane's faultsim delay rule latches onto to stall
            ONE rank's op entry (emulating compute skew)."""
            from ray_tpu.util import collective as col
            from ray_tpu.util.collective import collective as colmod

            arr = np.random.RandomState(seed).randn(
                max(1, nbytes // 4)).astype(np.float32)
            m = colmod._metrics()
            w0, l0 = m[0].default._value, m[1].default._value
            r0 = m[2].default._value
            durs, out, cc_done = [], None, []
            for _ in range(iters):
                x = arr.copy()
                if nudge:
                    colmod._kv_del_prefix(b"__skew:nudge__")
                t0 = time.perf_counter()
                out = col.allreduce(x, group, op=op)
                durs.append(time.perf_counter() - t0)
                cc_done.append(dict(colmod._group(group).peer_cc_done))
            res = {"durs": durs, "wire": m[0].default._value - w0,
                   "logical": m[1].default._value - l0,
                   "retries": m[2].default._value - r0,
                   "cc_done": cc_done}
            if return_out:
                res["out"] = np.asarray(out)
            return res

    def _row(name, durs, extra=None):
        d = np.array(durs) * 1e3
        row = {"benchmark": name, "value": round(float(np.median(d)), 3),
               "unit": "ms/op", "p50_ms": round(float(np.percentile(d, 50)), 3),
               "p95_ms": round(float(np.percentile(d, 95)), 3),
               "p99_ms": round(float(np.percentile(d, 99)), 3),
               "iters": len(durs)}
        if extra:
            row.update(extra)
        rows.append(row)
        print(f"{name:<44s} p50={row['p50_ms']:>9,.2f}ms "  # lint: allow-print
              f"p95={row['p95_ms']:>9,.2f}ms p99={row['p99_ms']:>9,.2f}ms"
              + (f"  {extra}" if extra else ""))
        return row

    def _fanout(workers, group, nbytes, iters, op="sum", return_out=False,
                nudge=False):
        outs = ray_tpu.get(
            [w.run_allreduce.remote(group, nbytes, iters, 1000 + r, op,
                                    return_out and r == 0, nudge)
             for r, w in enumerate(workers)], timeout=600)
        return outs

    gates: Dict[str, bool] = {}
    from ray_tpu.util import collective as col

    for world in worlds:
        workers = [ColWorker.remote() for _ in range(world)]
        for grp, quant in ((f"b{world}", ""), (f"q{world}", "int8")):
            col.create_collective_group(workers, world, list(range(world)),
                                        backend="store", group_name=grp,
                                        quant=quant)
        for nbytes, label, iters in sizes:
            # fp32 chunked (default config: 1MB chunks, pipelined)
            outs = _fanout(workers, f"b{world}", nbytes, iters)
            _row(f"allreduce fp32 {label} w{world}", outs[0]["durs"])
            # int8 quantized wire
            outs = _fanout(workers, f"q{world}", nbytes, iters, op="sum",
                           return_out=nbytes <= (1 << 20))
            wire, logical = outs[0]["wire"], outs[0]["logical"]
            ratio = logical / wire if wire else 0.0
            _row(f"allreduce int8 {label} w{world}", outs[0]["durs"],
                 {"wire_bytes": int(wire), "logical_bytes": int(logical),
                  "logical_over_wire": round(ratio, 2)})
            if nbytes == (1 << 20):
                # acceptance: quantized wire bytes <= 0.3x logical
                gates[f"int8_wire_w{world}"] = wire <= 0.3 * logical
            if "out" in outs[0]:
                # analytic per-block bound check against the true sum
                arrs = [np.random.RandomState(1000 + r).randn(
                    max(1, nbytes // 4)).astype(np.float32)
                    for r in range(world)]
                ref = np.sum(np.stack(arrs), axis=0)
                err = float(np.abs(outs[0]["out"] - ref).max())
                scales = [float(np.abs(a).max()) / 127.0 for a in arrs]
                bound = 0.5 * sum(scales) + 0.5 * float(
                    np.abs(ref).max()) / 127.0 + 1e-6
                gates[f"int8_err_{label}_w{world}"] = err <= bound

        # chunked-vs-monolithic A/B at the top size, fp32, best-of-N.
        # Force a chunk size well below the tensor so the "chunked" arm
        # actually chunks even in small mode (1MB tensors are NOT > the
        # 1MB default threshold and would silently route monolithic).
        nbytes, label, iters = sizes[-1]
        ab_chunk = min(cfg.collective_chunk_bytes or (1 << 20),
                       max(nbytes // 8, 64 << 10))
        ray_tpu.get([w.set_cfg.remote({"collective_chunk_bytes": 0})
                     for w in workers], timeout=30)
        mono = _fanout(workers, f"b{world}", nbytes, iters)
        _row(f"allreduce fp32 {label} w{world} monolithic", mono[0]["durs"])
        ray_tpu.get([w.set_cfg.remote({"collective_chunk_bytes": ab_chunk})
                     for w in workers], timeout=30)
        chunked = _fanout(workers, f"b{world}", nbytes, iters)
        _row(f"allreduce fp32 {label} w{world} chunked", chunked[0]["durs"])
        ray_tpu.get([w.set_cfg.remote(
            {"collective_chunk_bytes": cfg.collective_chunk_bytes})
            for w in workers], timeout=30)
        speedup = (min(mono[0]["durs"]) / min(chunked[0]["durs"])
                   if chunked[0]["durs"] else 0.0)
        rows.append({"benchmark": f"chunked speedup {label} w{world}",
                     "value": round(speedup, 2), "unit": "x (best-of-N)",
                     "chunk_bytes": ab_chunk,
                     "mono_best_ms": round(min(mono[0]["durs"]) * 1e3, 2),
                     "chunked_best_ms":
                         round(min(chunked[0]["durs"]) * 1e3, 2)})
        print(f"chunked speedup {label} w{world}: "  # lint: allow-print
              f"{speedup:.2f}x (mono best {min(mono[0]['durs'])*1e3:.1f}ms "
              f"-> chunked best {min(chunked[0]['durs'])*1e3:.1f}ms)")
        if nbytes >= (64 << 20):
            # the acceptance gates apply at the 64MB top size; small mode
            # stops at 1MB, where chunk overhead ~ pipelining win (noise)
            gates[f"chunked_not_slower_w{world}"] = speedup >= 1.0
            if world == 2:
                gates["chunked_speedup_target"] = speedup >= 1.3

    # -- skewed-rank sub-lane: rank 1 enters every op late (a faultsim
    # delay rule stalls its pre-op nudge RPC's write stream, emulating
    # compute skew); straggler-aware chunk deferral vs FIFO, measured on
    # fast rank 0. An allreduce's completion is ALWAYS bound by the
    # slowest contributor (every output chunk depends on the late
    # rank's input), so no fetch schedule can shrink single-op wall
    # clock here and the lane does not gate on it. What deferral buys —
    # and what overlap_grads monetizes — is fast ranks retiring
    # fast-peer work UNDER the straggler's delay instead of serialized
    # after it: FIFO parks the bounded pipeline windows on the late
    # rank's unpublished chunks, starving the fast peer's ready ones.
    # The gate reads rank 0's peer_cc_done: the offset into the fetch
    # loop when the FAST peer's last contribution chunk retired.
    slow_env = {"runtime_env": {"env_vars": {
        "RAY_TPU_RPC_FAULTS": "kv_del:delay:1:0:350"}}}
    skew_workers = [ColWorker.remote(),
                    ColWorker.options(**slow_env).remote(),
                    ColWorker.remote()]
    col.create_collective_group(skew_workers, 3, [0, 1, 2],
                                backend="store", group_name="skew")
    sk_bytes = (2 << 20) if small else (4 << 20)
    sk_iters = 4 if small else 6
    sk_cfg = {"collective_chunk_bytes": 64 << 10,
              "collective_pipeline_depth": 2}
    ray_tpu.get([w.set_cfg.remote(dict(sk_cfg,
                                       collective_straggler_threshold=0.0))
                 for w in skew_workers], timeout=30)
    _fanout(skew_workers, "skew", sk_bytes, 2, nudge=True)  # warmup
    fifo = _fanout(skew_workers, "skew", sk_bytes, sk_iters, nudge=True)
    frow = _row("allreduce skew w3 fifo", fifo[0]["durs"],
                {"retries": int(fifo[0]["retries"])})
    ray_tpu.get([w.set_cfg.remote(dict(sk_cfg,
                                       collective_straggler_threshold=0.05))
                 for w in skew_workers], timeout=30)
    _fanout(skew_workers, "skew", sk_bytes, 2, nudge=True)  # learn EWMA
    strag = _fanout(skew_workers, "skew", sk_bytes, sk_iters, nudge=True)
    srow = _row("allreduce skew w3 straggler-aware", strag[0]["durs"],
                {"retries": int(strag[0]["retries"])})

    def _fast_done_ms(outs):
        # rank 0's fast peer is rank 2 (rank 1 carries the delay rule)
        vals = [d[2] for d in outs[0]["cc_done"] if 2 in d]
        return round(float(np.median(vals)) * 1e3, 1) if vals else 0.0

    fifo_done, strag_done = _fast_done_ms(fifo), _fast_done_ms(strag)
    gates["straggler_beats_fifo"] = 0.0 < strag_done < fifo_done
    # sanity: deferral must not cost wall clock (10% tolerance for noise)
    gates["straggler_not_slower"] = srow["p50_ms"] <= 1.10 * frow["p50_ms"]
    rows.append({"benchmark": "skew w3 fast-peer cc retire",
                 "value": round(fifo_done / strag_done, 2)
                 if strag_done else 0.0,
                 "unit": "x (>1 = straggler-aware retires fast-peer "
                         "chunks earlier)",
                 "fifo_ms": fifo_done, "straggler_ms": strag_done,
                 "fifo_p50_ms": frow["p50_ms"],
                 "straggler_p50_ms": srow["p50_ms"]})
    print(f"skew w3 fast-peer cc retire: fifo {fifo_done}ms -> "  # lint: allow-print
          f"straggler-aware {strag_done}ms")

    rows.append({"benchmark": "collective gates",
                 "value": float(all(gates.values())), "unit": "all-pass",
                 "gates": gates})
    print(f"gates: {gates}")  # lint: allow-print
    return rows
