"""Runtime-env materialization: working_dir + py_modules.

ray parity: python/ray/_private/runtime_env/{packaging.py, working_dir.py,
py_modules.py} + the per-node agent (agent/runtime_env_agent.py:159) and
URI cache (uri_cache.py). TPU-native there is no separate agent process:
the DRIVER packages local directories into content-addressed zips stored
in the GCS KV, rewriting the runtime_env to carry URIs; each WORKER
materializes the URIs it needs into a node-local cache before serving
tasks (workers are pooled per runtime-env hash, so one worker serves one
env). pip IS supported offline through a local wheelhouse (see
_PipPlugin: the wheelhouse ships content-addressed like working_dir and
workers build a cached venv from it); conda works against pre-created
named envs; container wraps the worker command in a podman/docker
invocation (_ContainerPlugin + raylet spawn wrapping).
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Dict, List, Optional

_KV_NS = b"runtime_env_packages"
MAX_PACKAGE_BYTES = 200 * 1024 * 1024
# driver-side: (driver client_id, abspath) -> uploaded digest. Keyed per
# connection so a digest cached against one cluster is never trusted on a
# fresh cluster whose KV lacks the package; content changes during one
# driver's lifetime are not re-detected (the reference packages per job).
_UPLOAD_CACHE: dict = {}

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def package_directory(path: str) -> tuple:
    """Zip a directory into (content_hash, zip_bytes). Deterministic:
    sorted entries, zeroed timestamps — equal trees hash equal."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory not found: {path}")
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS
                         and not d.startswith("."))
        for f in sorted(files):
            if f.startswith("."):
                continue
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, path), full))
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            total += os.path.getsize(full)
            if total > MAX_PACKAGE_BYTES:
                raise ValueError(
                    f"runtime_env package exceeds "
                    f"{MAX_PACKAGE_BYTES >> 20}MB: {path}"
                )
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            with open(full, "rb") as fh:
                zf.writestr(info, fh.read())
    blob = buf.getvalue()
    digest = hashlib.sha256(blob).hexdigest()[:24]
    return digest, blob


# ---------------------------------------------------------------------------
# Plugin framework (ray parity: _private/runtime_env/plugin.py:24 —
# RuntimeEnvPlugin with per-key validate/create hooks, priority-ordered).
# The built-in keys (working_dir, py_modules, env_vars) are plugins of the
# same registry user plugins join via register_runtime_env_plugin.
# ---------------------------------------------------------------------------


class RuntimeEnvPlugin:
    """One runtime_env key's handling. ``validate`` runs driver-side at
    option time (fail fast); ``prepare`` runs driver-side and may rewrite
    the env dict (e.g. path -> URI); ``materialize`` runs in each worker
    before it serves tasks."""

    name: str = ""
    priority: int = 50  # lower runs first (working_dir before py_modules)

    def validate(self, env: dict) -> None:
        pass

    def prepare(self, core_worker, env: dict) -> None:
        pass

    def materialize(self, core_worker, env: dict) -> None:
        pass


_PLUGINS: dict = {}


def register_runtime_env_plugin(plugin: RuntimeEnvPlugin):
    """Add a custom runtime_env key (ray parity: the plugin framework's
    entry-point registration). The plugin's ``name`` is the env dict key
    it owns."""
    if not plugin.name:
        raise ValueError("plugin needs a name (the runtime_env key it owns)")
    _PLUGINS[plugin.name] = plugin


def _ordered_plugins():
    return sorted(_PLUGINS.values(), key=lambda p: p.priority)


def prepare_runtime_env(core_worker, runtime_env: Optional[dict]
                        ) -> Optional[dict]:
    """Driver-side: run every registered plugin's validate+prepare
    (ray: upload_package_to_gcs and friends). Idempotent on already-
    prepared envs; unsupported keys raise early."""
    if not runtime_env:
        return runtime_env
    env = dict(runtime_env)
    for plugin in _ordered_plugins():
        plugin.validate(env)
        plugin.prepare(core_worker, env)
    # the raylet ships the env to workers as JSON; a non-JSON value (set,
    # bytes, ...) must fail HERE at option time, not inside the raylet's
    # dispatch loop
    import json

    try:
        json.dumps({k: v for k, v in env.items() if k != "env_vars"})
    except TypeError as e:
        raise ValueError(
            f"runtime_env values must be JSON-serializable: {e}"
        ) from None
    return env


def _upload_factory(core_worker):
    def upload(path: str) -> str:
        # One walk+zip+upload per path per driver process: repeated
        # .remote() calls with the same working_dir must not re-hash the
        # tree on every submission (ray packages per job, not per task).
        abspath = os.path.abspath(os.path.expanduser(path))
        cache_key = (core_worker.client_id, abspath)
        cached = _UPLOAD_CACHE.get(cache_key)
        if cached is not None:
            return cached
        digest, blob = package_directory(path)
        key = digest.encode()
        exists = core_worker.io.run(core_worker.gcs.request(
            "kv_exists", {"ns": _KV_NS, "key": key}
        ))
        if not exists:
            core_worker.io.run(core_worker.gcs.request(
                "kv_put", {"ns": _KV_NS, "key": key, "value": blob}
            ))
        _UPLOAD_CACHE[cache_key] = digest
        return digest

    return upload


class _WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 10

    def prepare(self, core_worker, env: dict) -> None:
        if env.get("working_dir") and not env.get("working_dir_uri"):
            upload = _upload_factory(core_worker)
            env["working_dir_uri"] = upload(env.pop("working_dir"))

    def materialize(self, core_worker, env: dict) -> None:
        wd_uri = env.get("working_dir_uri")
        if not wd_uri:
            return
        path = _fetch_and_extract(_gcs_requester(core_worker), wd_uri)
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)


class _PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 20

    def prepare(self, core_worker, env: dict) -> None:
        if env.get("py_modules") and not env.get("py_module_uris"):
            upload = _upload_factory(core_worker)
            uris = []
            for mod_path in env.pop("py_modules"):
                uris.append((os.path.basename(os.path.normpath(mod_path)),
                             upload(mod_path)))
            env["py_module_uris"] = uris

    def materialize(self, core_worker, env: dict) -> None:
        for name, uri in env.get("py_module_uris") or ():
            path = _fetch_and_extract(_gcs_requester(core_worker), uri)
            # extracted dir IS the module content; expose it under its name
            parent = os.path.join(_cache_root(), f"mods_{uri}")
            os.makedirs(parent, exist_ok=True)
            link = os.path.join(parent, name)
            if not os.path.exists(link):
                try:
                    os.symlink(path, link)
                except OSError:
                    pass
            if parent not in sys.path:
                sys.path.insert(0, parent)


class _EnvVarsPlugin(RuntimeEnvPlugin):
    """env_vars apply at worker SPAWN (the raylet exports them before the
    interpreter starts, so sitecustomize/jax see them); this plugin only
    validates shape."""

    name = "env_vars"
    priority = 5

    def validate(self, env: dict) -> None:
        ev = env.get("env_vars")
        if ev is None:
            return
        if not isinstance(ev, dict) or not all(
            isinstance(k, str) for k in ev
        ):
            raise ValueError("runtime_env['env_vars'] must be a str dict")


class _PipPlugin(RuntimeEnvPlugin):
    """pip runtime env backed by a LOCAL WHEELHOUSE (ray parity:
    python/ray/_private/runtime_env/pip.py, constrained to offline
    images: no index access at task time).

    Accepted forms::

        runtime_env={"pip": ["mypkg", "otherpkg==1.2"]}
        runtime_env={"pip": {"packages": [...],
                             "wheelhouse": "/path/to/wheels"}}

    The wheelhouse (the dict key, or ``RAY_TPU_WHEELHOUSE``) must be a
    directory of pre-downloaded wheels; validation fails EARLY with a
    clear error when none is configured, rather than at task time. The
    driver uploads the wheelhouse as a content-addressed package to the
    GCS KV (same plane as working_dir), so remote nodes materialize it
    too and updated wheels change the content hash (no stale-venv
    trap). Workers build a ``--system-site-packages`` venv per
    (packages, wheelhouse-content) digest under the node cache —
    atomically, via tmp-dir + rename, because concurrent same-env
    workers race — install with ``pip --no-index --find-links``, and
    add the venv's site-packages to ``sys.path``.

    Priority 8: BEFORE working_dir/py_modules, whose later sys.path
    prepends must shadow wheelhouse packages (user-shipped code wins
    over installed packages, matching the reference's precedence)."""

    name = "pip"
    priority = 8

    @staticmethod
    def _normalize(env: dict):
        spec = env.get("pip")
        if not spec:
            return None, None
        if isinstance(spec, (list, tuple)):
            packages, wheelhouse = list(spec), None
        elif isinstance(spec, dict):
            packages = list(spec.get("packages") or ())
            wheelhouse = spec.get("wheelhouse")
        else:
            raise ValueError(
                "runtime_env['pip'] must be a list of requirements or a "
                "dict with 'packages' (+ optional 'wheelhouse')"
            )
        wheelhouse = wheelhouse or os.environ.get("RAY_TPU_WHEELHOUSE")
        return packages, wheelhouse

    def validate(self, env: dict) -> None:
        spec = env.get("pip")
        if isinstance(spec, dict) and spec.get("wheelhouse_uri"):
            return  # already prepared (validate is re-run on re-prepare)
        packages, wheelhouse = self._normalize(env)
        if packages is None:
            return
        if not packages:
            raise ValueError("runtime_env['pip'] lists no packages")
        if not wheelhouse:
            raise ValueError(
                "runtime_env['pip'] needs a local wheelhouse in this "
                "offline image: pass {'pip': {'packages': [...], "
                "'wheelhouse': '/path/to/wheels'}} or set "
                "RAY_TPU_WHEELHOUSE. There is no network package "
                "installation at task time; pre-download wheels with "
                "`pip download -d <wheelhouse> <pkgs>` on a connected "
                "machine."
            )
        if not os.path.isdir(wheelhouse):
            raise ValueError(
                f"runtime_env['pip'] wheelhouse {wheelhouse!r} is not a "
                "directory"
            )

    def prepare(self, core_worker, env: dict) -> None:
        spec = env.get("pip")
        if isinstance(spec, dict) and spec.get("wheelhouse_uri"):
            return  # already prepared
        packages, wheelhouse = self._normalize(env)
        if packages is None:
            return
        # ship the wheelhouse content-addressed through the GCS KV: the
        # driver-local path means nothing on other nodes, and the content
        # hash doubles as the venv cache key (updated wheels -> new venv)
        upload = _upload_factory(core_worker)
        env["pip"] = {"packages": sorted(packages),
                      "wheelhouse_uri": upload(wheelhouse)}

    def materialize(self, core_worker, env: dict) -> None:
        import shutil
        import subprocess

        spec = env.get("pip")
        if not spec:
            return
        packages = list(spec.get("packages") or ())
        uri = spec.get("wheelhouse_uri")
        if not packages or not uri:
            return
        wheelhouse = _fetch_and_extract(_gcs_requester(core_worker), uri)
        digest = hashlib.sha256(
            repr((sorted(packages), uri)).encode()
        ).hexdigest()[:16]
        venv_dir = os.path.join(_cache_root(), f"pipenv_{digest}")
        marker = os.path.join(venv_dir, ".ready")
        if not os.path.exists(marker):
            # build in a private tmp dir and publish with one atomic
            # rename; a concurrent same-env worker either wins the rename
            # or discards its build and uses the winner's
            tmp = f"{venv_dir}.building.{os.getpid()}"
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 tmp],
                check=True, capture_output=True,
            )
            proc = subprocess.run(
                [os.path.join(tmp, "bin", "pip"), "install", "--no-index",
                 "--find-links", wheelhouse, *sorted(packages)],
                capture_output=True, text=True,
            )
            if proc.returncode != 0:
                shutil.rmtree(tmp, ignore_errors=True)
                raise RuntimeError(
                    "pip runtime_env install failed (wheelhouse "
                    f"{wheelhouse}):\n{proc.stdout}\n{proc.stderr}"
                )
            with open(os.path.join(tmp, ".ready"), "w") as f:
                f.write("ok")
            try:
                os.rename(tmp, venv_dir)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
        import glob as _glob

        for sp in _glob.glob(
            os.path.join(venv_dir, "lib", "python*", "site-packages")
        ):
            if sp not in sys.path:
                sys.path.insert(0, sp)


class _CondaPlugin(RuntimeEnvPlugin):
    """conda runtime env (ray parity:
    python/ray/_private/runtime_env/conda.py), constrained like pip to
    what an offline image can honor:

    - ``{"conda": "env-name"}`` activates an EXISTING named env: its
      site-packages are prepended to ``sys.path`` worker-side (the same
      in-process activation the pip plugin uses for venvs).
    - ``{"conda": {...env spec...}}`` (env creation) needs a conda binary
      and network/channel access — validation fails EARLY with a clear
      error if no conda binary is on this image, rather than at task time.
    """

    name = "conda"
    priority = 8

    @staticmethod
    def _conda_exe():
        import shutil as _sh

        return (os.environ.get("CONDA_EXE")
                or _sh.which("conda") or _sh.which("mamba"))

    @classmethod
    def _named_env_prefix(cls, name: str):
        """Resolve a named env: cheap directory probes first
        ($CONDA_PREFIX/envs/<name>, ~/.conda/envs/<name>, the root prefix
        itself), then — so custom envs_dirs configurations resolve too —
        `conda env list --json` when a binary exists."""
        roots = []
        base = os.environ.get("CONDA_PREFIX")
        if base:
            # CONDA_PREFIX may itself be an env dir; its parent of parent
            # is the install root
            roots += [base, os.path.dirname(os.path.dirname(base))]
        roots.append(os.path.expanduser("~/.conda"))
        for root in roots:
            cand = os.path.join(root, "envs", name)
            if os.path.isdir(cand):
                return cand
        if base and os.path.basename(base) == name:
            return base
        exe = cls._conda_exe()
        if exe:
            import json as _json
            import subprocess

            try:
                out = subprocess.run(
                    [exe, "env", "list", "--json"], capture_output=True,
                    text=True, timeout=30,
                )
                for prefix in _json.loads(out.stdout or "{}").get(
                    "envs", []
                ):
                    if os.path.basename(prefix) == name:
                        return prefix
            except Exception:
                pass
        return None

    def validate(self, env: dict) -> None:
        spec = env.get("conda")
        if not spec:
            return
        if isinstance(spec, str):
            if self._named_env_prefix(spec) is None and not self._conda_exe():
                raise ValueError(
                    f"runtime_env['conda'] names env {spec!r}, but no such "
                    "env directory exists and no conda binary is available "
                    "to resolve it. Pre-create the env on every node or "
                    "use runtime_env['pip'] with a local wheelhouse."
                )
        elif isinstance(spec, dict):
            if not self._conda_exe():
                raise ValueError(
                    "runtime_env['conda'] with an env spec needs a conda "
                    "binary, which this image does not ship. Use a named "
                    "pre-created env ({'conda': 'name'}) or "
                    "runtime_env['pip'] with a local wheelhouse."
                )
        else:
            raise ValueError(
                "runtime_env['conda'] must be an env name or an env spec "
                "dict"
            )

    def materialize(self, core_worker, env: dict) -> None:
        import glob as _glob
        import subprocess

        spec = env.get("conda")
        if not spec:
            return
        if isinstance(spec, dict):
            exe = self._conda_exe()
            if exe is None:
                # validate ran driver-side; this node may differ
                raise RuntimeError(
                    "runtime_env['conda'] env spec: no conda binary on "
                    "this node"
                )
            # env creation path: hash the spec; build in a private tmp
            # prefix and publish with ONE atomic rename (same recipe as
            # the pip venvs above — a failed or concurrent create must
            # never leave a half-built prefix that later workers treat
            # as ready)
            digest = hashlib.sha256(
                repr(sorted(spec.items())).encode()
            ).hexdigest()[:16]
            prefix = os.path.join(_cache_root(), f"condaenv_{digest}")
            if not os.path.isdir(prefix):
                import shutil
                import tempfile

                with tempfile.NamedTemporaryFile(
                    "w", suffix=".yml", delete=False
                ) as f:
                    import yaml as _yaml

                    _yaml.safe_dump(spec, f)
                    spec_file = f.name
                tmp = f"{prefix}.building.{os.getpid()}"
                try:
                    proc = subprocess.run(
                        [exe, "env", "create", "-p", tmp, "-f", spec_file],
                        capture_output=True, text=True,
                    )
                finally:
                    try:
                        os.unlink(spec_file)
                    except OSError:
                        pass
                if proc.returncode != 0:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeError(
                        f"conda env create failed:\n{proc.stderr}"
                    )
                try:
                    os.rename(tmp, prefix)
                except OSError:  # lost the publish race: use the winner's
                    shutil.rmtree(tmp, ignore_errors=True)
        else:
            prefix = self._named_env_prefix(spec)
            if prefix is None:
                raise RuntimeError(
                    f"conda env {spec!r} not found on this node"
                )
        for sp in _glob.glob(
            os.path.join(prefix, "lib", "python*", "site-packages")
        ):
            if sp not in sys.path:
                sys.path.insert(0, sp)


class _ContainerPlugin(RuntimeEnvPlugin):
    """runtime_env={"container": {"image": ..., "run_options": [...],
    "engine": "podman"|"docker"|<path>}} — the raylet wraps the worker
    command in a container invocation (ray parity:
    _private/runtime_env/container.py, which wraps with podman). The
    image must carry the same python + ray_tpu importable; network/ipc
    stay on the host namespaces so the worker reaches the raylet and
    the /dev/shm object store zero-copy."""

    name = "container"
    priority = 5  # shape-validate before packaging work

    def validate(self, env: dict) -> None:
        c = env.get("container")
        if not c:
            return
        if not isinstance(c, dict) or not c.get("image"):
            raise ValueError(
                "runtime_env['container'] must be a dict with an 'image' "
                f"key (got {c!r})"
            )
        ro = c.get("run_options", [])
        if not isinstance(ro, (list, tuple)) or not all(
            isinstance(o, str) for o in ro
        ):
            raise ValueError(
                "runtime_env['container']['run_options'] must be a list "
                "of strings"
            )

    # materialize: nothing to do inside the worker — by the time the
    # worker runs, it IS in the container (the raylet did the wrapping)


def build_container_command(container: dict, env: Dict[str, str],
                            inner_argv: List[str],
                            extra_env_keys: tuple = (),
                            cidfile: Optional[str] = None) -> List[str]:
    """The worker argv wrapped in a container engine invocation.

    Host network + IPC + **PID** namespaces and /dev/shm + the session
    dir bind-mounted: the control plane (raylet/GCS ports, pid-keyed
    worker registration), the data plane (mmap'd object files), and
    signal delivery must look identical inside the container. The
    repository root rides along read-only so images without ray_tpu
    baked in still work for same-version clusters.

    ``extra_env_keys``: additional env names to forward (the caller's
    runtime_env env_vars + accelerator triggers — the prefix filter
    below only covers cluster plumbing). ``cidfile``: engine writes the
    container id there so the raylet can force-remove a container whose
    client process it had to kill (SIGKILL never proxies).
    """
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg

    engine = container.get("engine") or cfg.container_runtime
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))
    cmd = [engine, "run", "--rm", "--network=host", "--ipc=host",
           "--pid=host", "-v", "/dev/shm:/dev/shm"]
    if cidfile:
        cmd += ["--cidfile", cidfile]
    session = env.get("RAY_TPU_SESSION_DIR")
    if session:
        cmd += ["-v", f"{session}:{session}"]
    cmd += ["-v", f"{repo_root}:{repo_root}:ro",
            "-e", f"PYTHONPATH={repo_root}"]
    for k, v in env.items():
        if k.startswith(("RAY_TPU_", "JAX_", "XLA_")) \
                or k in extra_env_keys:
            cmd += ["-e", f"{k}={v}"]
    cmd += list(container.get("run_options", []))
    cmd.append(container["image"])
    return cmd + list(inner_argv)


register_runtime_env_plugin(_ContainerPlugin())
register_runtime_env_plugin(_CondaPlugin())
register_runtime_env_plugin(_PipPlugin())
register_runtime_env_plugin(_EnvVarsPlugin())
register_runtime_env_plugin(_WorkingDirPlugin())
register_runtime_env_plugin(_PyModulesPlugin())


def _load_env_plugins():
    """Load plugin classes named in RAY_TPU_RUNTIME_ENV_PLUGINS
    ("module:Class,module2:Class2") — the cross-process registration
    path: workers are separate interpreters, so a plugin registered by
    driver code alone would never materialize worker-side (ray parity:
    the RAY_RUNTIME_ENV_PLUGINS class-path env var)."""
    spec = os.environ.get("RAY_TPU_RUNTIME_ENV_PLUGINS", "")
    for item in filter(None, (s.strip() for s in spec.split(","))):
        try:
            mod_name, _, cls_name = item.partition(":")
            import importlib

            cls = getattr(importlib.import_module(mod_name), cls_name)
            register_runtime_env_plugin(cls())
        except Exception:  # a broken plugin must not kill every process
            import logging

            logging.getLogger(__name__).exception(
                "failed to load runtime_env plugin %r", item
            )


_load_env_plugins()


def _cache_root() -> str:
    base = os.environ.get("RAY_TPU_SESSION_DIR") or "/tmp"
    return os.path.join(base, "runtime_env_cache")


def _fetch_and_extract(gcs_request, uri: str) -> str:
    """Materialize one package URI into the node-local cache (ray:
    uri_cache.py — content-addressed, so concurrent extracts converge)."""
    target = os.path.join(_cache_root(), uri)
    if os.path.isdir(target):
        return target
    blob = gcs_request("kv_get", {"ns": _KV_NS, "key": uri.encode()})
    if blob is None:
        raise RuntimeError(f"runtime_env package {uri} missing from GCS")
    tmp = target + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:  # lost the race: someone else extracted it
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


def _gcs_requester(core_worker):
    def gcs_request(method, payload):
        return core_worker.io.run(core_worker.gcs.request(method, payload))

    return gcs_request


def materialize(core_worker, runtime_env: Optional[dict]) -> None:
    """Worker-side: run every plugin's materialize before this worker
    serves tasks (ray: RuntimeEnvAgent.CreateRuntimeEnv). working_dir
    becomes the process CWD and lands on sys.path; py_modules land on
    sys.path under their original import names; custom plugins run in
    priority order."""
    if not runtime_env:
        return
    for plugin in _ordered_plugins():
        plugin.materialize(core_worker, runtime_env)
