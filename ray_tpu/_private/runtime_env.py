"""Runtime-env materialization: working_dir + py_modules.

ray parity: python/ray/_private/runtime_env/{packaging.py, working_dir.py,
py_modules.py} + the per-node agent (agent/runtime_env_agent.py:159) and
URI cache (uri_cache.py). TPU-native there is no separate agent process:
the DRIVER packages local directories into content-addressed zips stored
in the GCS KV, rewriting the runtime_env to carry URIs; each WORKER
materializes the URIs it needs into a node-local cache before serving
tasks (workers are pooled per runtime-env hash, so one worker serves one
env). pip/conda are not supported in this offline image and raise
up front rather than failing at task time.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Optional

_KV_NS = b"runtime_env_packages"
MAX_PACKAGE_BYTES = 200 * 1024 * 1024
# driver-side: (driver client_id, abspath) -> uploaded digest. Keyed per
# connection so a digest cached against one cluster is never trusted on a
# fresh cluster whose KV lacks the package; content changes during one
# driver's lifetime are not re-detected (the reference packages per job).
_UPLOAD_CACHE: dict = {}

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def package_directory(path: str) -> tuple:
    """Zip a directory into (content_hash, zip_bytes). Deterministic:
    sorted entries, zeroed timestamps — equal trees hash equal."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory not found: {path}")
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS
                         and not d.startswith("."))
        for f in sorted(files):
            if f.startswith("."):
                continue
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, path), full))
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            total += os.path.getsize(full)
            if total > MAX_PACKAGE_BYTES:
                raise ValueError(
                    f"runtime_env package exceeds "
                    f"{MAX_PACKAGE_BYTES >> 20}MB: {path}"
                )
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            with open(full, "rb") as fh:
                zf.writestr(info, fh.read())
    blob = buf.getvalue()
    digest = hashlib.sha256(blob).hexdigest()[:24]
    return digest, blob


def prepare_runtime_env(core_worker, runtime_env: Optional[dict]
                        ) -> Optional[dict]:
    """Driver-side: package local dirs, upload to the GCS KV, rewrite the
    env to URI form (ray: upload_package_to_gcs). Idempotent on already-
    prepared envs; validates unsupported plugins early."""
    if not runtime_env:
        return runtime_env
    for unsupported in ("pip", "conda", "container"):
        if runtime_env.get(unsupported):
            raise ValueError(
                f"runtime_env[{unsupported!r}] is not supported in this "
                "offline build (no package installation at task time); "
                "bake dependencies into the image"
            )
    env = dict(runtime_env)

    def upload(path: str) -> str:
        # One walk+zip+upload per path per driver process: repeated
        # .remote() calls with the same working_dir must not re-hash the
        # tree on every submission (ray packages per job, not per task).
        abspath = os.path.abspath(os.path.expanduser(path))
        cache_key = (core_worker.client_id, abspath)
        cached = _UPLOAD_CACHE.get(cache_key)
        if cached is not None:
            return cached
        digest, blob = package_directory(path)
        key = digest.encode()
        exists = core_worker.io.run(core_worker.gcs.request(
            "kv_exists", {"ns": _KV_NS, "key": key}
        ))
        if not exists:
            core_worker.io.run(core_worker.gcs.request(
                "kv_put", {"ns": _KV_NS, "key": key, "value": blob}
            ))
        _UPLOAD_CACHE[cache_key] = digest
        return digest

    if env.get("working_dir") and not env.get("working_dir_uri"):
        env["working_dir_uri"] = upload(env.pop("working_dir"))
    if env.get("py_modules") and not env.get("py_module_uris"):
        uris = []
        for mod_path in env.pop("py_modules"):
            uris.append((os.path.basename(os.path.normpath(mod_path)),
                         upload(mod_path)))
        env["py_module_uris"] = uris
    return env


def _cache_root() -> str:
    base = os.environ.get("RAY_TPU_SESSION_DIR") or "/tmp"
    return os.path.join(base, "runtime_env_cache")


def _fetch_and_extract(gcs_request, uri: str) -> str:
    """Materialize one package URI into the node-local cache (ray:
    uri_cache.py — content-addressed, so concurrent extracts converge)."""
    target = os.path.join(_cache_root(), uri)
    if os.path.isdir(target):
        return target
    blob = gcs_request("kv_get", {"ns": _KV_NS, "key": uri.encode()})
    if blob is None:
        raise RuntimeError(f"runtime_env package {uri} missing from GCS")
    tmp = target + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:  # lost the race: someone else extracted it
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


def materialize(core_worker, runtime_env: Optional[dict]) -> None:
    """Worker-side: download + extract this worker's env before it serves
    tasks (ray: RuntimeEnvAgent.CreateRuntimeEnv). working_dir becomes the
    process CWD and lands on sys.path; py_modules land on sys.path under
    their original import names."""
    if not runtime_env:
        return

    def gcs_request(method, payload):
        return core_worker.io.run(core_worker.gcs.request(method, payload))

    wd_uri = runtime_env.get("working_dir_uri")
    if wd_uri:
        path = _fetch_and_extract(gcs_request, wd_uri)
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
    for name, uri in runtime_env.get("py_module_uris") or ():
        path = _fetch_and_extract(gcs_request, uri)
        # extracted dir IS the module content; expose it under its name
        parent = os.path.join(_cache_root(), f"mods_{uri}")
        os.makedirs(parent, exist_ok=True)
        link = os.path.join(parent, name)
        if not os.path.exists(link):
            try:
                os.symlink(path, link)
            except OSError:
                pass
        if parent not in sys.path:
            sys.path.insert(0, parent)
