"""ObjectRef: a future for a task return or put object.

Like the reference's ObjectRef (ray: python/ray/includes/object_ref.pxi), each
ref carries the binary ObjectID plus the owner's address so any holder can
locate the value (ownership-based object directory,
ray: src/ray/object_manager/ownership_based_object_directory.h). Refs support
``ray.get`` via the connected core worker and are serializable; serializing a
ref inside task args registers it as a dependency via a thread-local capture
list (ray: python/ray/_private/serialization.py object-ref capture).
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu._private.ids import ObjectID

_capture = threading.local()


def start_ref_capture():
    _capture.refs = []


def captured_refs():
    return getattr(_capture, "refs", [])


def stop_ref_capture():
    refs = getattr(_capture, "refs", [])
    _capture.refs = None
    return refs


class ObjectRef:
    __slots__ = ("_id", "_owner", "_hash", "_counted", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[tuple] = None):
        # owner: (node_id_hex, client_id_hex) of the owning core worker.
        self._id = object_id
        self._owner = owner
        self._hash = hash(object_id)
        # Set by CoreWorker.add_local_ref: this Python object holds one local
        # refcount on the owned object, released in __del__.
        self._counted = False

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    @property
    def owner(self):
        return self._owner

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        refs = getattr(_capture, "refs", None)
        if refs is not None:
            refs.append(self)
        return (_rebuild_ref, (self._id.binary(), self._owner))

    def __del__(self):
        # NEVER release synchronously: __del__ runs at arbitrary GC points,
        # including inside core-worker sections that already hold the
        # ref-count lock (a same-thread re-acquire deadlocks). Enqueue the
        # release on a lock-free deque the worker drains outside its lock.
        if not getattr(self, "_counted", False):
            return
        try:
            from ray_tpu._private.worker import global_worker

            cw = global_worker.core_worker
            if cw is not None and cw.connected:
                cw.defer_ref_release(self._id.binary())
        except Exception:
            pass

    def future(self):
        """Return a concurrent.futures.Future for this ref (via core worker)."""
        from ray_tpu._private.worker import global_worker

        return global_worker.core_worker.future_for(self)

    def __await__(self):
        import asyncio

        fut = self.future()
        return asyncio.wrap_future(fut).__await__()


def _rebuild_ref(binary: bytes, owner):
    ref = ObjectRef(ObjectID(binary), owner)
    # When deserialized inside a connected worker, record the borrow so the
    # owner keeps the value alive (simplified borrower protocol,
    # ray: src/ray/core_worker/reference_count.h:61).
    try:
        from ray_tpu._private.worker import global_worker

        if global_worker.connected:
            global_worker.core_worker.register_borrowed_ref(ref)
    except Exception:
        pass
    return ref
