"""Value (de)serialization for the object plane.

Analog of the reference's SerializationContext
(ray: python/ray/_private/serialization.py:108): cloudpickle for closures +
pickle protocol 5 out-of-band buffers so numpy / jax host arrays round-trip
through the shm store without copies on the read side. A serialized value is

  metadata: pickled {"fmt": ..., "buf_lens": [...], "nested_refs": [...]}
  data:     [8B pickle_len][pickle bytes][buffer 0][buffer 1]...

Errors are serialized with fmt="error" so ``get`` re-raises on the caller
(ray: python/ray/exceptions.py RayTaskError semantics).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

import cloudpickle

from ray_tpu._private import object_ref as _object_ref

FMT_PICKLE5 = b"P5"
FMT_ERROR = b"ER"
FMT_RAW = b"RW"  # raw bytes payload, zero-copy


def _nbytes(b) -> int:
    return b.nbytes if isinstance(b, memoryview) else len(b)


class BufferList:
    """Wire form of a serialized value's data: the ordered buffer list of a
    ``SerializedValue`` (``[8B pickle_len][pickle][buf0][buf1]...``) kept as
    separate buffers instead of one joined blob.

    Pickling a BufferList under protocol 5 wraps each large member in a
    ``PickleBuffer``: over a v2 rpc connection those ride the frame's
    out-of-band buffer table — the payload bytes are written to the socket
    by reference and arrive as zero-copy memoryviews over the receiver's
    read buffer. Over a v1 connection (or any protocol-5 pickle without a
    buffer_callback) the same members serialize in-band — one copy, same
    bytes — so mixed-version peers interoperate. Unpickling yields a
    BufferList of bytes/memoryview members in the original order;
    ``deserialize`` consumes either form.
    """

    __slots__ = ("buffers",)

    def __init__(self, buffers):
        self.buffers = buffers if isinstance(buffers, list) else list(buffers)

    @property
    def nbytes(self) -> int:
        return sum(_nbytes(b) for b in self.buffers)

    def concat(self) -> bytes:
        bufs = self.buffers
        if len(bufs) == 1 and isinstance(bufs[0], bytes):
            return bufs[0]
        return b"".join(bufs)

    def __reduce_ex__(self, protocol):
        if protocol >= 5:
            # same tunable the connection's buffer_callback applies: below
            # it, a table entry + unjoined write costs more than the memcpy
            from ray_tpu._private.config import GLOBAL_CONFIG

            oob_min = GLOBAL_CONFIG.rpc_oob_min_bytes
            return (BufferList, ([
                pickle.PickleBuffer(b) if _nbytes(b) >= oob_min
                else (b if isinstance(b, bytes) else bytes(b))
                for b in self.buffers
            ],))
        return (BufferList, ([
            b if isinstance(b, bytes) else bytes(b) for b in self.buffers
        ],))


class SerializedValue:
    __slots__ = ("metadata", "buffers", "total_data_len", "nested_refs")

    def __init__(self, metadata, buffers, total_data_len, nested_refs):
        self.metadata = metadata
        self.buffers = buffers
        self.total_data_len = total_data_len
        self.nested_refs = nested_refs

    def to_bytes(self) -> bytes:
        """Materialize the data as ONE bytes object (a snapshot: exactly one
        copy per buffer via join; buffers already bytes are returned or
        joined without an intermediate ``bytes(b)`` copy)."""
        bufs = self.buffers
        if len(bufs) == 1 and isinstance(bufs[0], bytes):
            return bufs[0]  # raw-bytes value: no copy at all
        return b"".join(bufs)

    def to_wire(self) -> BufferList:
        """Zero-copy wire form: the live buffer list (views into the value
        being serialized — e.g. a numpy array's memory). Large members cross
        v2 rpc frames out-of-band without ever being copied on the send
        side. Because the views alias the caller's value, the caller must
        not mutate the underlying buffers until the send completes (for a
        task call: until its result future resolves)."""
        return BufferList(self.buffers)


def _pack(fmt: bytes, pickled: bytes, oob: List, nested_refs) -> SerializedValue:
    buf_lens = [len(b) for b in oob]
    meta = pickle.dumps(
        {"fmt": fmt, "buf_lens": buf_lens, "nested_refs": nested_refs}, protocol=5
    )
    buffers = [len(pickled).to_bytes(8, "little"), pickled] + oob
    total = 8 + len(pickled) + sum(buf_lens)
    return SerializedValue(meta, buffers, total, nested_refs)


def serialize(value: Any) -> SerializedValue:
    if isinstance(value, bytes):
        meta = pickle.dumps({"fmt": FMT_RAW, "buf_lens": [], "nested_refs": []})
        return SerializedValue(meta, [value], len(value), [])
    oob: List = []

    def buffer_callback(pb: pickle.PickleBuffer):
        view = pb.raw()
        # store-layout threshold (distinct from the wire's
        # rpc_oob_min_bytes): tiny buffers stay inside the pickled stream
        if view.nbytes >= 512:
            oob.append(view)
            return False
        return True

    _object_ref.start_ref_capture()
    try:
        pickled = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
        nested = [(r.binary(), r.owner) for r in _object_ref.captured_refs()]
    finally:
        _object_ref.stop_ref_capture()
    return _pack(FMT_PICKLE5, pickled, oob, nested)


def serialize_error(exc: BaseException, task_info: str = "") -> SerializedValue:
    import traceback

    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        payload = cloudpickle.dumps((exc, tb, task_info), protocol=5)
    except Exception:
        payload = cloudpickle.dumps(
            (RuntimeError(f"{type(exc).__name__}: {exc}"), tb, task_info), protocol=5
        )
    return _pack(FMT_ERROR, payload, [], [])


class TaskError(Exception):
    """Wraps an exception raised inside a task, carrying the remote traceback.

    Analog of ray.exceptions.RayTaskError: re-raised at every ``get`` site.
    """

    def __init__(self, cause: BaseException, remote_traceback: str, task_info: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_info = task_info
        super().__init__(str(cause))

    def __reduce__(self):
        return (type(self), (self.cause, self.remote_traceback, self.task_info))

    def __str__(self):
        return (
            f"{type(self.cause).__name__}: {self.cause}\n"
            f"--- remote traceback ({self.task_info}) ---\n{self.remote_traceback}"
        )


def deserialize(metadata: bytes, data) -> Any:
    """Deserialize from metadata + data, where ``data`` is a bytes-like view
    (zero-copy capable) or a ``BufferList`` as received off a v2 rpc frame
    (zero-copy: buffers are consumed in place, never joined)."""
    meta = pickle.loads(metadata)
    fmt = meta["fmt"]
    if isinstance(data, BufferList):
        bufs = data.buffers
        # fast path: the list still has _pack's structure
        # [8B pickle_len][pickle][oob buffers matching buf_lens] — feed the
        # out-of-band buffers straight to pickle without reassembly
        if (
            fmt != FMT_RAW
            and len(bufs) == len(meta["buf_lens"]) + 2
            and _nbytes(bufs[0]) == 8
            and int.from_bytes(bytes(bufs[0]), "little") == _nbytes(bufs[1])
            and all(
                _nbytes(b) == n for b, n in zip(bufs[2:], meta["buf_lens"])
            )
        ):
            value = pickle.loads(
                bufs[1], buffers=[memoryview(b) for b in bufs[2:]]
            )
            if fmt == FMT_ERROR:
                exc, tb, info = value
                raise TaskError(exc, tb, info)
            return value
        data = data.concat()  # re-chunked upstream: fall through
    if fmt == FMT_RAW and isinstance(data, bytes):
        return data
    view = memoryview(data)
    if fmt == FMT_RAW:
        return bytes(view)
    plen = int.from_bytes(bytes(view[:8]), "little")
    pickled = view[8 : 8 + plen]
    offset = 8 + plen
    buffers = []
    for blen in meta["buf_lens"]:
        buffers.append(view[offset : offset + blen])
        offset += blen
    # pickle.loads takes any buffer: feed the envelope as a view so a
    # slab/mmap-backed read never copies the pickle blob either — the
    # whole deserialize is views into the arena mapping
    value = pickle.loads(pickled, buffers=buffers)
    if fmt == FMT_ERROR:
        exc, tb, info = value
        raise TaskError(exc, tb, info)
    return value
