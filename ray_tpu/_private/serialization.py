"""Value (de)serialization for the object plane.

Analog of the reference's SerializationContext
(ray: python/ray/_private/serialization.py:108): cloudpickle for closures +
pickle protocol 5 out-of-band buffers so numpy / jax host arrays round-trip
through the shm store without copies on the read side. A serialized value is

  metadata: pickled {"fmt": ..., "buf_lens": [...], "nested_refs": [...]}
  data:     [8B pickle_len][pickle bytes][buffer 0][buffer 1]...

Errors are serialized with fmt="error" so ``get`` re-raises on the caller
(ray: python/ray/exceptions.py RayTaskError semantics).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

import cloudpickle

from ray_tpu._private import object_ref as _object_ref

FMT_PICKLE5 = b"P5"
FMT_ERROR = b"ER"
FMT_RAW = b"RW"  # raw bytes payload, zero-copy


class SerializedValue:
    __slots__ = ("metadata", "buffers", "total_data_len", "nested_refs")

    def __init__(self, metadata, buffers, total_data_len, nested_refs):
        self.metadata = metadata
        self.buffers = buffers
        self.total_data_len = total_data_len
        self.nested_refs = nested_refs

    def to_bytes(self) -> bytes:
        return b"".join(bytes(b) for b in self.buffers)


def _pack(fmt: bytes, pickled: bytes, oob: List, nested_refs) -> SerializedValue:
    buf_lens = [len(b) for b in oob]
    meta = pickle.dumps(
        {"fmt": fmt, "buf_lens": buf_lens, "nested_refs": nested_refs}, protocol=5
    )
    buffers = [len(pickled).to_bytes(8, "little"), pickled] + oob
    total = 8 + len(pickled) + sum(buf_lens)
    return SerializedValue(meta, buffers, total, nested_refs)


def serialize(value: Any) -> SerializedValue:
    if isinstance(value, bytes):
        meta = pickle.dumps({"fmt": FMT_RAW, "buf_lens": [], "nested_refs": []})
        return SerializedValue(meta, [value], len(value), [])
    oob: List = []

    def buffer_callback(pb: pickle.PickleBuffer):
        view = pb.raw()
        if view.nbytes >= 512:  # keep tiny buffers in-band
            oob.append(view)
            return False
        return True

    _object_ref.start_ref_capture()
    try:
        pickled = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
        nested = [(r.binary(), r.owner) for r in _object_ref.captured_refs()]
    finally:
        _object_ref.stop_ref_capture()
    return _pack(FMT_PICKLE5, pickled, oob, nested)


def serialize_error(exc: BaseException, task_info: str = "") -> SerializedValue:
    import traceback

    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        payload = cloudpickle.dumps((exc, tb, task_info), protocol=5)
    except Exception:
        payload = cloudpickle.dumps(
            (RuntimeError(f"{type(exc).__name__}: {exc}"), tb, task_info), protocol=5
        )
    return _pack(FMT_ERROR, payload, [], [])


class TaskError(Exception):
    """Wraps an exception raised inside a task, carrying the remote traceback.

    Analog of ray.exceptions.RayTaskError: re-raised at every ``get`` site.
    """

    def __init__(self, cause: BaseException, remote_traceback: str, task_info: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_info = task_info
        super().__init__(str(cause))

    def __reduce__(self):
        return (type(self), (self.cause, self.remote_traceback, self.task_info))

    def __str__(self):
        return (
            f"{type(self.cause).__name__}: {self.cause}\n"
            f"--- remote traceback ({self.task_info}) ---\n{self.remote_traceback}"
        )


def deserialize(metadata: bytes, data) -> Any:
    """Deserialize from metadata + a bytes-like data view (zero-copy capable)."""
    meta = pickle.loads(metadata)
    fmt = meta["fmt"]
    view = memoryview(data)
    if fmt == FMT_RAW:
        return bytes(view)
    plen = int.from_bytes(bytes(view[:8]), "little")
    pickled = view[8 : 8 + plen]
    offset = 8 + plen
    buffers = []
    for blen in meta["buf_lens"]:
        buffers.append(view[offset : offset + blen])
        offset += blen
    value = pickle.loads(bytes(pickled), buffers=buffers)
    if fmt == FMT_ERROR:
        exc, tb, info = value
        raise TaskError(exc, tb, info)
    return value
