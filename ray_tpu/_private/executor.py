"""Worker-side task execution.

Analog of the reference's task execution path
(ray: python/ray/_raylet.pyx:1770 task_execution_handler / :1607 execute_task
plus ray: src/ray/core_worker/transport/actor_scheduling_queue.h): deserialize
args (zero-copy from the shm store), run the user function on an executor
thread (or the user asyncio loop for async actor methods), serialize returns
(small values travel in-band back to the owner; large ones are written
straight into the node's shm store by this process), and enforce per-caller
sequence ordering for actor calls.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import logging
import os
import threading
import time
import traceback
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import logplane, object_store, profiler, serialization
from ray_tpu._private.common import TaskSpec
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import ObjectID, TaskID

logger = logging.getLogger(__name__)


# --- runtime metrics: per-actor-class queue-wait + run-time ------------
class _ExecMetrics:
    __slots__ = ("run", "wait", "_children")

    def __init__(self):
        from ray_tpu._private import metrics_core as mc

        reg = mc.registry()
        self.run = reg.histogram(
            "worker_task_run_seconds",
            "User-code execution time per task, by actor class "
            "('task' for plain tasks)", scale=mc.LATENCY)
        self.wait = reg.histogram(
            "worker_task_queue_wait_seconds",
            "Executor queue wait: request arrival to user-code start "
            "(includes the actor sequence gate)", scale=mc.LATENCY)
        self._children: Dict[str, tuple] = {}

    def record(self, kind: str, wait_s: float, run_s: float):
        pair = self._children.get(kind)
        if pair is None:
            pair = self._children[kind] = (
                self.wait.labels(kind=kind), self.run.labels(kind=kind))
        pair[0].record(wait_s)
        pair[1].record(run_s)


_MX: Optional[_ExecMetrics] = None


def _exec_metrics() -> _ExecMetrics:
    global _MX
    if _MX is None:
        _MX = _ExecMetrics()
    return _MX


class _CallerQueue:
    """Per-caller sequence gate (ray: sequential_actor_submit_queue.h).

    One future PER SEQUENCE NUMBER, released exactly when its turn
    arrives. A Condition with notify_all here is O(queue) wakeups per
    advance — with 2k pipelined calls that profiled at 3.4M wait cycles
    (the 1:1 async actor bottleneck); this form is O(1) per advance."""

    def __init__(self):
        self.next_seq = 0
        self.waiters: Dict[int, asyncio.Future] = {}


class TaskExecutor:
    def __init__(self, core_worker):
        self.cw = core_worker
        self.pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task-exec"
        )
        self.max_concurrency = 1
        # Declared concurrency groups → per-group asyncio.Semaphore
        # ("_default" caps ungrouped methods at max_concurrency). Empty
        # when the actor declares no groups. ray parity:
        # src/ray/core_worker/transport/concurrency_group_manager.h
        self._group_sems: Dict[str, asyncio.Semaphore] = {}
        self.actor_instance: Any = None
        self.actor_spec: Optional[TaskSpec] = None
        self._caller_queues: Dict[bytes, _CallerQueue] = {}
        self._user_loop: Optional[asyncio.AbstractEventLoop] = None
        self._user_loop_started = threading.Event()
        self._async_sem: Optional[asyncio.Semaphore] = None
        self.current_task_id: Optional[bytes] = None
        self.current_job_id: Optional[bytes] = None
        # Publish last: the core worker's IO thread polls `executor` and may
        # dispatch a task the instant it becomes visible.
        core_worker.executor = self

    # ------------------------------------------------------------------
    def _ensure_user_loop(self):
        if self._user_loop is not None:
            return
        def run():
            loop = asyncio.new_event_loop()
            self._user_loop = loop
            asyncio.set_event_loop(loop)
            self._user_loop_started.set()
            loop.run_forever()
        threading.Thread(target=run, name="actor-async", daemon=True).start()
        self._user_loop_started.wait()

    # ------------------------------------------------------------------
    async def become_actor(self, spec: TaskSpec):
        try:
            cls = cloudpickle.loads(spec.func_blob)
            args, kwargs = await self._resolve_args(spec)
            self.max_concurrency = max(1, spec.max_concurrency)
            groups = dict(spec.concurrency_groups or {})
            if groups:
                # Declaring groups makes the actor concurrent: each group
                # gets its own admission semaphore, ungrouped methods share
                # the "_default" group capped at max_concurrency, and the
                # thread pool is sized so no group can starve another.
                self._group_sems = {
                    name: asyncio.Semaphore(cap) for name, cap in groups.items()
                }
                self._group_sems["_default"] = asyncio.Semaphore(
                    self.max_concurrency
                )
                # Total threads = every group saturated at once.
                self.max_concurrency += sum(groups.values())
            if self.max_concurrency > 1:
                self.pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self.max_concurrency, thread_name_prefix="actor-exec"
                )
            self.actor_spec = spec
            self.current_job_id = spec.job_id
            loop = asyncio.get_running_loop()
            instance = await loop.run_in_executor(self.pool, lambda: cls(*args, **kwargs))
            self.actor_instance = instance
            return {}
        except Exception as e:
            tb = traceback.format_exc()
            logger.error("actor init failed: %s", tb)
            return {"error": f"{type(e).__name__}: {e}\n{tb}"}

    # ------------------------------------------------------------------
    def _observe_submit_to_run(self, spec: TaskSpec):
        """BENCH_CONTROL_PLANE dispatch stage: wall-clock gap between the
        driver stamping the spec (TaskSpec.submit_time) and this worker
        starting on it — submit RPC + lease/queue wait + dispatch in one
        number (same-box clocks; the bench runs single-host)."""
        dt = time.time() - spec.submit_time
        if dt < 0:
            return
        from ray_tpu._private.worker import _stage_record

        _stage_record("submit_to_run", dt)

    async def execute_task(self, spec: TaskSpec):
        t_in = time.perf_counter()
        if cfg.control_plane_stage_timing:
            self._observe_submit_to_run(spec)
        is_actor_task = spec.actor_id is not None and not spec.actor_creation
        sem = None
        if is_actor_task and (self._group_sems or spec.concurrency_group):
            group = spec.concurrency_group or "_default"
            sem = self._group_sems.get(group)
            if sem is None:
                err = ValueError(
                    f"unknown concurrency group {group!r}; this actor "
                    f"declares {sorted(g for g in self._group_sems if g != '_default')}"
                )
                return self._error_result(
                    serialization.serialize_error(err, spec.name),
                    app_error=False,
                )
        if is_actor_task and self.max_concurrency == 1:
            await self._await_turn(spec.caller_id, spec.seq_no)
        if sem is not None:
            async with sem:
                return await self._execute_gated(spec, is_actor_task, t_in)
        return await self._execute_gated(spec, is_actor_task, t_in)

    # ------------------------------------------------------------------
    def _batchable(self, spec: TaskSpec) -> bool:
        """May this call join a single-thread-hop batch run? Plain sync
        task functions, or strictly sequential (max_concurrency 1,
        ungrouped) SYNC actor methods — exactly the calls whose semantics
        a sequential in-order run cannot change. Dynamic-return and traced
        calls take the per-spec path."""
        if getattr(spec, "tracing_ctx", None) is not None:
            return False
        if spec.num_returns == -1:
            return False
        if spec.actor_id is None:
            fn = self._load_fn(spec.func_blob)
            return not inspect.iscoroutinefunction(fn)
        if spec.actor_creation:
            return False
        if self.actor_instance is None or self.max_concurrency != 1:
            return False
        if self._group_sems or spec.concurrency_group:
            return False
        method = getattr(self.actor_instance, spec.method_name, None)
        return method is not None and not inspect.iscoroutinefunction(method)

    async def execute_task_batch(self, specs, deliver):
        """Batched execution with STREAMED results: ``deliver(spec,
        result)`` is awaited the moment each task's result exists, so an
        early task is never gated on the batch tail (ray.wait semantics).
        Consecutive batchable sync calls share ONE thread-pool submission
        (one SimpleQueue hop + GIL handoff instead of one per call — the
        dominant worker-side cost for short calls); each completion still
        streams out of the run individually, so a slow task inside a run
        delays nobody behind it being DELIVERED, only executed."""
        pending = []
        i, n = 0, len(specs)
        while i < n:
            if self._batchable(specs[i]):
                lead_plain = specs[i].actor_id is None
                k = 1
                while (i + k < n and self._batchable(specs[i + k])
                       and (specs[i + k].actor_id is None) == lead_plain):
                    k += 1
                await self._execute_sync_run(specs[i:i + k], deliver)
            else:
                # Non-batchable (async functions, dynamic returns, traced):
                # dispatch CONCURRENTLY, exactly as separate execute_task
                # requests would have — awaiting inline would serialize
                # async tasks and deadlock co-batched tasks that
                # coordinate with each other.
                k = 1

                async def run_one(s=specs[i]):
                    await deliver(s, await self.execute_task(s))

                pending.append(asyncio.ensure_future(run_one()))
            i += k
        for t in pending:
            await t

    async def _execute_sync_run(self, specs, deliver):
        """Run a contiguous burst of batchable calls in one pool hop,
        streaming each completion back to the loop thread as it happens
        (call_soon_threadsafe -> queue -> package + deliver). For actor
        calls the seq gate is awaited for the FIRST spec only: the burst
        is one caller's contiguous seq range, so once its head may run
        the rest follow in order inside the same pool submission; each
        call's turn advances as its result streams out, so later frames'
        calls unblock without waiting for the run tail. Plain tasks have
        no ordering contract and skip the gate."""
        loop = asyncio.get_running_loop()
        start = time.time()
        t_in = time.perf_counter()
        if cfg.control_plane_stage_timing:
            for s in specs:
                self._observe_submit_to_run(s)
        gated = specs[0].actor_id is not None
        if gated:
            await self._await_turn(specs[0].caller_id, specs[0].seq_no)
        done_q: asyncio.Queue = asyncio.Queue()
        # per-item (start, end) log offsets, written by the pool thread in
        # each item's finally BEFORE its done_q put (happens-before via
        # call_soon_threadsafe), read when packaging that item's result
        log_spans: list = [None] * len(specs)
        log_file = logplane.worker_log_path()
        delivered = 0
        try:
            resolved = []
            for spec in specs:
                try:
                    resolved.append(("ok", await self._resolve_args(spec)))
                except serialization.TaskError as e:
                    # dependency failed: propagate its error as ours
                    resolved.append(("err", serialization.serialize_error(
                        e.cause, spec.name), True))
                except Exception as e:
                    resolved.append(("err", serialization.serialize_error(
                        e, spec.name), False))
            self.current_job_id = specs[0].job_id
            self.cw.job_id = specs[0].job_id

            calls = [
                (getattr(self.actor_instance, spec.method_name)
                 if spec.actor_id is not None
                 else self._load_fn(spec.func_blob))
                for spec in specs
            ]

            kind = self._metric_kind(specs[0])

            def run_all():
                for idx, (spec, r, call) in enumerate(
                    zip(specs, resolved, calls)
                ):
                    if r[0] != "ok":
                        loop.call_soon_threadsafe(
                            done_q.put_nowait, (idx, False, None)
                        )
                        continue
                    args, kwargs = r[1]
                    self.current_task_id = spec.task_id
                    t_start = time.perf_counter()
                    # log attribution: exact byte range of this item's
                    # stdout/stderr in the worker log (stdio flushed on
                    # both edges, so batch neighbors never bleed)
                    log_start = logplane.stdio_offset()
                    try:
                        with profiler.tag_current_thread.for_spec(spec):
                            out = (idx, True, call(*args, **kwargs))
                    except Exception as e:
                        out = (idx, False, e)
                    finally:
                        log_spans[idx] = (log_start, logplane.stdio_offset())
                        self.current_task_id = None
                        # wait = batch arrival at the executor to THIS
                        # item's user-code start (seq gate + arg resolve
                        # + time behind earlier batch items), matching
                        # the non-batch path's arrival-to-start contract
                        _exec_metrics().record(
                            kind, t_start - t_in,
                            time.perf_counter() - t_start)
                    loop.call_soon_threadsafe(done_q.put_nowait, out)

            pool_fut = loop.run_in_executor(self.pool, run_all)
            for _ in range(len(specs)):
                idx, ok, value = await done_q.get()
                spec, r = specs[idx], resolved[idx]
                if r[0] != "ok":
                    result = self._error_result(r[1], app_error=r[2])
                elif ok:
                    result = self._package_returns(spec, value, start)
                else:
                    result = self._error_result(
                        serialization.serialize_error(value, spec.name),
                        app_error=True,
                    )
                span = log_spans[idx]
                if (log_file and span and span[0] is not None
                        and span[1] is not None):
                    result["log_span"] = {
                        "file": os.path.basename(log_file),
                        "start": span[0], "end": max(span[1], span[0]),
                    }
                if gated:
                    await self._advance_turn(spec.caller_id)
                delivered += 1
                await deliver(spec, result)
            await pool_fut
        finally:
            if gated:
                # crash path: later frames' calls must not deadlock on
                # turns the dead run will never advance
                for _ in range(len(specs) - delivered):
                    await self._advance_turn(specs[0].caller_id)

    async def _execute_gated(self, spec: TaskSpec, is_actor_task: bool,
                             t_in: Optional[float] = None):
        try:
            ctx = getattr(spec, "tracing_ctx", None)
            if ctx is not None:
                # A propagated span context means the submitter traces:
                # record this execution as a child span (ray:
                # tracing_helper.py _inject_tracing_into_function).
                # Stateless on purpose — concurrent tasks on this loop must
                # not share thread-local span stacks, and the span must
                # record even when _execute raises.
                from ray_tpu.util import tracing

                # Pre-generate this execution span's id so nested .remote()
                # calls from the task body chain to THIS hop (the user-code
                # thread adopts {trace, exec_span_id} as its context).
                exec_span_id = tracing.new_span_id()
                spec.tracing_ctx = {
                    "trace_id": ctx["trace_id"], "span_id": exec_span_id,
                }
                start = time.time()
                try:
                    return await self._execute(spec, is_actor_task, t_in)
                finally:
                    tracing.record_remote_span(
                        f"task::{spec.name}", start, time.time(), ctx,
                        attributes={"task_id": spec.task_id.hex()[:16]},
                        span_id=exec_span_id,
                    )
            return await self._execute(spec, is_actor_task, t_in)
        finally:
            if is_actor_task and self.max_concurrency == 1:
                await self._advance_turn(spec.caller_id)

    async def _await_turn(self, caller_id: bytes, seq_no: int):
        q = self._caller_queues.get(caller_id)
        if q is None:
            # First task from this caller: adopt its sequence number. After an
            # actor restart the caller's counter keeps increasing, so the gate
            # must re-anchor rather than wait for seq 0 (which already ran in
            # the previous incarnation).
            q = _CallerQueue()
            q.next_seq = seq_no
            self._caller_queues[caller_id] = q
        if q.next_seq >= seq_no:
            return
        fut = q.waiters.get(seq_no)
        if fut is None:
            fut = q.waiters[seq_no] = \
                asyncio.get_running_loop().create_future()
        await fut

    async def _advance_turn(self, caller_id: bytes):
        q = self._caller_queues.setdefault(caller_id, _CallerQueue())
        q.next_seq += 1
        fut = q.waiters.pop(q.next_seq, None)
        if fut is not None and not fut.done():
            fut.set_result(None)

    def _metric_kind(self, spec: TaskSpec) -> str:
        if spec.actor_id is not None and self.actor_spec is not None:
            return self.actor_spec.name or "actor"
        return "task"

    async def _execute(self, spec: TaskSpec, is_actor_task: bool,
                       t_in: Optional[float] = None):
        loop = asyncio.get_running_loop()
        start = time.time()
        self.current_task_id = spec.task_id
        self.current_job_id = spec.job_id
        # Nested submissions from this task belong to the task's job.
        self.cw.job_id = spec.job_id
        try:
            args, kwargs = await self._resolve_args(spec)
        except serialization.TaskError as e:
            # A dependency failed: propagate its error as ours.
            sv = serialization.serialize_error(e.cause, spec.name)
            return self._error_result(sv, app_error=True)
        except Exception as e:
            sv = serialization.serialize_error(e, spec.name)
            return self._error_result(sv, app_error=False)
        t_run = time.perf_counter()
        # log attribution: byte range of this task's output in the worker
        # log (exact; stamped onto the result for the task-event pipeline)
        log_start = logplane.stdio_offset()
        try:
            ctx = getattr(spec, "tracing_ctx", None)
            if is_actor_task:
                method = getattr(self.actor_instance, spec.method_name)
                if inspect.iscoroutinefunction(method):
                    self._ensure_user_loop()
                    cfut = asyncio.run_coroutine_threadsafe(
                        self._run_async_method(method, args, kwargs), self._user_loop
                    )
                    value = await asyncio.wrap_future(cfut)
                else:
                    value = await loop.run_in_executor(
                        self.pool,
                        lambda: self._invoke_user(
                            spec, lambda: method(*args, **kwargs), ctx
                        ),
                    )
            else:
                func = self._load_fn(spec.func_blob)
                if inspect.iscoroutinefunction(func):
                    self._ensure_user_loop()
                    cfut = asyncio.run_coroutine_threadsafe(
                        func(*args, **kwargs), self._user_loop
                    )
                    value = await asyncio.wrap_future(cfut)
                else:
                    value = await loop.run_in_executor(
                        self.pool,
                        lambda: self._invoke_user(
                            spec, lambda: func(*args, **kwargs), ctx
                        ),
                    )
        except Exception as e:
            sv = serialization.serialize_error(e, spec.name)
            return logplane.attach_result_span(
                self._error_result(sv, app_error=True), log_start)
        finally:
            self.current_task_id = None
            _exec_metrics().record(
                self._metric_kind(spec),
                (t_run - t_in) if t_in is not None else 0.0,
                time.perf_counter() - t_run,
            )
        return logplane.attach_result_span(
            self._package_returns(spec, value, start), log_start)

    def _load_fn(self, func_blob: bytes):
        """Deserialize a task function with a digest-keyed cache: a driver
        loop calling the same @remote function thousands of times must not
        pay cloudpickle.loads per execution (ray parity: the function
        table caches by function id in _raylet.pyx)."""
        import hashlib

        key = hashlib.md5(func_blob).digest()
        cache = getattr(self, "_fn_cache", None)
        if cache is None:
            cache = self._fn_cache = {}
        fn = cache.get(key)
        if fn is None:
            fn = cloudpickle.loads(func_blob)
            if len(cache) >= 256:  # bound: long-lived workers, many jobs
                cache.pop(next(iter(cache)))
            cache[key] = fn
        return fn

    def _invoke_user(self, spec, fn, ctx):
        """Run user code on a pool thread with the sampling profiler's
        thread tag set (per-task/actor attribution in CPU profiles) on
        top of the traced invocation."""
        with profiler.tag_current_thread.for_spec(spec):
            return self._invoke_traced(fn, ctx)

    @staticmethod
    def _invoke_traced(fn, ctx):
        """Run user code on a pool thread with the propagated span context
        adopted thread-locally, so nested .remote() submissions stay in the
        submitter's trace (multi-hop). Pool threads run one task function
        at a time, so the thread-local cannot leak across tasks."""
        if ctx is None:
            return fn()
        from ray_tpu.util import tracing

        tracing.set_remote_context(ctx)
        try:
            return fn()
        finally:
            tracing.set_remote_context(None)

    async def _run_async_method(self, method, args, kwargs):
        if self._async_sem is None or self._async_sem._value > self.max_concurrency:
            self._async_sem = asyncio.Semaphore(self.max_concurrency)
        async with self._async_sem:
            return await method(*args, **kwargs)

    # ------------------------------------------------------------------
    async def _resolve_args(self, spec: TaskSpec):
        args = [await self._resolve_one(a) for a in spec.args]
        kwargs = {k: await self._resolve_one(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    async def _resolve_one(self, slot):
        from ray_tpu._private.worker import _deser_container

        kind = slot[0]
        if kind == "v":
            return serialization.deserialize(slot[1], slot[2])
        oid_bytes = slot[1]
        oid = ObjectID(oid_bytes)
        buf = object_store.read_object(self.cw.store_dir, oid)
        if buf is None:
            ok = await self.cw.raylet.request(
                "pull_object",
                {"object_id": oid_bytes,
                 "owner": slot[2] if len(slot) > 2 else None})
            if not ok.get("ok"):
                raise RuntimeError(f"task argument {oid_bytes.hex()[:16]} unavailable")
            buf = object_store.read_object(self.cw.store_dir, oid)
            if buf is None:
                raise RuntimeError(f"task argument {oid_bytes.hex()[:16]} unavailable")
        # Do not release the buffer: returned values may alias the mmap; the
        # mapping stays alive as long as any view does (plasma zero-copy).
        # Refs nested in the value are borrowed *through* this argument
        # object; record the provenance for the borrower handoff.
        with _deser_container(oid_bytes):
            return serialization.deserialize(buf.metadata, buf.data)

    # ------------------------------------------------------------------
    def _package_returns(self, spec: TaskSpec, value: Any, start: float):
        if spec.num_returns == -1:  # num_returns="dynamic"
            return self._package_dynamic_returns(spec, value, start)
        values = (value,) if spec.num_returns == 1 else tuple(value)
        if spec.num_returns > 1 and len(values) != spec.num_returns:
            sv = serialization.serialize_error(
                ValueError(
                    f"task returned {len(values)} values, expected {spec.num_returns}"
                ),
                spec.name,
            )
            return self._error_result(sv, app_error=True)
        results = []
        stored = []
        returns_nested = {}
        return_pins = []
        tid = TaskID(spec.task_id)
        for i, v in enumerate(values):
            try:
                sv = serialization.serialize(v)
            except Exception as e:
                esv = serialization.serialize_error(e, spec.name)
                for t in return_pins:
                    self.cw.unpin_object(t)
                return self._error_result(esv, app_error=True)
            if sv.nested_refs:
                # Refs escaping via a return value: pin them here until the
                # caller has registered as their borrower and acks with
                # release_return_pins (reference_count.h return handoff).
                returns_nested[i] = list(sv.nested_refs)
                for oid_b, owner in sv.nested_refs:
                    return_pins.append(self.cw.pin_object(oid_b, owner))
            if sv.total_data_len <= cfg.max_direct_call_object_size:
                # wire form: large result buffers ride the v2 frame
                # out-of-band, never copied into the pickle stream
                results.append(("v", sv.metadata, sv.to_wire()))
            else:
                oid = ObjectID.from_index(tid, i + 1)
                # slab-arena write (batched accounting); one-file fallback
                self.cw.store_put(oid, sv)
                stored.append(oid.binary())
                results.append(("r", oid.binary()))
        if return_pins:
            with self.cw._lock:
                self.cw._return_pins[spec.task_id] = return_pins
            # Fallback: if the caller dies before acking release_return_pins,
            # expire the pins instead of pinning the objects forever.
            self.cw.io.call_soon(self._expire_return_pins(spec.task_id))
        return {
            "results": results,
            "stored_objects": stored,
            "duration": time.time() - start,
            # Borrower-protocol report (ray: PushTaskReply.borrowed_refs):
            # borrows this worker still holds (e.g. refs stashed in actor
            # state) so the owner can register us before releasing arg pins.
            "exec_addr": self.cw.addr,
            "borrows_kept": self.cw.borrowed_refs_held(),
            "returns_nested": returns_nested or None,
        }

    def _package_dynamic_returns(self, spec: TaskSpec, value: Any,
                                 start: float):
        """num_returns="dynamic" (ray: task_manager.h ObjectRefStream /
        legacy dynamic generators): the task returns an iterable of unknown
        length; each yielded item is stored as its own object (return index
        2, 3, ... — index 1 is the ref-list itself) and the single visible
        return resolves to the list of ObjectRefs. The caller adopts
        ownership of the item objects from the result notification
        (dynamic_return_oids), so lineage reconstruction re-executes this
        task if an item's plasma copy is lost."""
        from ray_tpu._private.object_ref import ObjectRef

        tid = TaskID(spec.task_id)
        item_oids = []
        returns_nested = {}
        return_pins = []
        try:
            for i, item in enumerate(value):
                sv = serialization.serialize(item)
                oid = ObjectID.from_index(tid, i + 2)
                self.cw.store_put(oid, sv)
                item_oids.append(oid.binary())
                if sv.nested_refs:
                    # refs escaping inside a yielded value: same handoff as
                    # plain returns — pinned here until the caller registers
                    # as borrower and acks (keyed so the caller's
                    # from_index(key+1) lands on THIS item, index i+2)
                    returns_nested[i + 1] = list(sv.nested_refs)
                    for oid_b, owner in sv.nested_refs:
                        return_pins.append(self.cw.pin_object(oid_b, owner))
        except Exception as e:
            # a partial run must not orphan the items already written
            # (slab entries are marked dead, fallback files unlinked)
            for oid_b in item_oids:
                try:
                    object_store.discard_local(
                        self.cw.store_dir, ObjectID(oid_b)
                    )
                except OSError:
                    pass
            for t in return_pins:
                self.cw.unpin_object(t)
            esv = serialization.serialize_error(e, spec.name)
            return self._error_result(esv, app_error=True)
        refs = [
            ObjectRef(ObjectID(oid), tuple(spec.owner)) for oid in item_oids
        ]
        sv = serialization.serialize(refs)
        results = [("v", sv.metadata, sv.to_wire())]
        if return_pins:
            with self.cw._lock:
                self.cw._return_pins[spec.task_id] = return_pins
            self.cw.io.call_soon(self._expire_return_pins(spec.task_id))
        return {
            "results": results,
            "stored_objects": list(item_oids),
            "dynamic_return_oids": list(item_oids),
            "duration": time.time() - start,
            "exec_addr": self.cw.addr,
            "borrows_kept": self.cw.borrowed_refs_held(),
            "returns_nested": returns_nested or None,
        }

    async def _expire_return_pins(self, task_id: bytes):
        await asyncio.sleep(cfg.borrower_poll_timeout_s)
        with self.cw._lock:
            pins = self.cw._return_pins.pop(task_id, None)
        for token in pins or ():
            self.cw.unpin_object(token)

    def _error_result(self, sv: serialization.SerializedValue, app_error: bool):
        return {
            "results": None,
            "error": "task raised" if app_error else "task system error",
            "error_value": (sv.metadata, sv.to_wire()),
            "app_error": app_error,
            "retriable": True,
            # Even a failed task may have stashed arg refs (actor state):
            # report them so the owner keeps those objects alive.
            "exec_addr": self.cw.addr,
            "borrows_kept": self.cw.borrowed_refs_held(),
        }
