"""Global Control Service: head-node metadata server + cluster-level scheduling.

Analog of the reference's GcsServer (ray: src/ray/gcs/gcs_server/gcs_server.h:79)
composing sub-managers: node membership + health (gcs_node_manager.h,
gcs_health_check_manager.h), cluster resource view (gcs_resource_manager.h),
actor lifetime + fault tolerance (gcs_actor_manager.h, gcs_actor_scheduler.h),
placement groups (gcs_placement_group_manager.h, 2-phase prepare/commit),
jobs (gcs_job_manager.h), internal KV (gcs_kv_manager.h), pubsub
(pubsub_handler.h), and the object directory (here centralized; the reference
uses owner-based lookup). State lives in a pluggable store (in-memory dict
now; the interface allows a persistent backend for GCS fault tolerance).

Raylets and drivers hold persistent duplex connections; the GCS pushes
cluster-view updates and actor/node pubsub over them.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Dict, List, Optional, Set

from ray_tpu._private import faultsim
from ray_tpu._private.common import NodeInfo, TaskSpec, place_bundles, res_fits
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.rpcio import Connection, RpcServer, spawn

logger = logging.getLogger(__name__)

# Actor states (ray: gcs.proto ActorTableData.ActorState)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class ActorRecord:
    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.actor_id: bytes = spec.actor_id
        self.state = PENDING_CREATION
        self.node_id: Optional[str] = None
        self.address: Optional[tuple] = None  # (node_id_hex, worker_client_id)
        # (host, port) of the actor worker's own RPC server; drivers push
        # calls straight there (ray: direct actor call transport)
        self.direct_addr: Optional[tuple] = None
        self.num_restarts = 0
        self.name = spec.name_registered
        self.namespace = spec.namespace or "default"
        self.death_cause: Optional[str] = None
        self.owner_conn_key: Optional[str] = None  # owning driver/worker client id

    def dump(self) -> dict:
        """Persistable form (everything a restarted GCS needs to resume
        managing this actor, incl. the creation spec for restarts)."""
        return {
            "spec": self.spec,
            "state": self.state,
            "node_id": self.node_id,
            "address": self.address,
            "direct_addr": self.direct_addr,
            "num_restarts": self.num_restarts,
            "death_cause": self.death_cause,
            "owner_conn_key": self.owner_conn_key,
        }

    @classmethod
    def restore(cls, d: dict) -> "ActorRecord":
        rec = cls(d["spec"])
        rec.state = d["state"]
        rec.node_id = d["node_id"]
        rec.address = tuple(d["address"]) if d["address"] else None
        rec.direct_addr = tuple(d["direct_addr"]) if d.get("direct_addr") else None
        rec.num_restarts = d["num_restarts"]
        rec.death_cause = d["death_cause"]
        rec.owner_conn_key = d.get("owner_conn_key")
        return rec

    def to_table(self):
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "node_id": self.node_id,
            "address": self.address,
            "direct_addr": self.direct_addr,
            "name": self.name,
            "namespace": self.namespace,
            "num_restarts": self.num_restarts,
            "class_name": self.spec.name,
            "death_cause": self.death_cause,
            "pid": None,
        }


class PlacementGroupRecord:
    def __init__(self, pg_id: str, bundles, strategy: str, name: str, job_id: bytes,
                 lifetime: Optional[str]):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.job_id = job_id
        self.lifetime = lifetime
        self.state = "PENDING"
        self.bundle_nodes: List[Optional[str]] = [None] * len(bundles)
        # topology-aware scheduling provenance (topology.py): the torus
        # coord per bundle host, the ring-overlap contention score of the
        # chosen placement, which scoring path chose it
        # ("topology-contention" | "resource-fit"), and how many pending
        # bundles the fragmentation repack pass migrated to place it
        self.node_coords: List[Optional[str]] = [None] * len(bundles)
        self.contention_score: Optional[float] = None
        self.sched_strategy: str = "resource-fit"
        self.repack_moves: int = 0

    def dump(self) -> dict:
        return {
            "pg_id": self.pg_id, "bundles": self.bundles,
            "strategy": self.strategy, "name": self.name,
            "job_id": self.job_id, "lifetime": self.lifetime,
            "state": self.state, "bundle_nodes": self.bundle_nodes,
            "node_coords": self.node_coords,
            "contention_score": self.contention_score,
            "sched_strategy": self.sched_strategy,
            "repack_moves": self.repack_moves,
        }

    @classmethod
    def restore(cls, d: dict) -> "PlacementGroupRecord":
        pg = cls(d["pg_id"], d["bundles"], d["strategy"], d["name"],
                 d["job_id"], d["lifetime"])
        pg.state = d["state"]
        pg.bundle_nodes = list(d["bundle_nodes"])
        pg.node_coords = list(d.get("node_coords")
                              or [None] * len(pg.bundles))
        pg.contention_score = d.get("contention_score")
        pg.sched_strategy = d.get("sched_strategy", "resource-fit")
        pg.repack_moves = d.get("repack_moves", 0)
        return pg

    def to_table(self):
        return {
            "placement_group_id": self.pg_id,
            "name": self.name,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "state": self.state,
            "bundle_nodes": self.bundle_nodes,
            "node_coords": self.node_coords,
            "contention_score": self.contention_score,
            "sched_strategy": self.sched_strategy,
            "repack_moves": self.repack_moves,
        }


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None,
                 cluster_id: Optional[str] = None):
        from ray_tpu._private.gcs_store import make_store

        self.server = RpcServer(self, host, port)
        self.nodes: Dict[str, NodeInfo] = {}
        self.node_conns: Dict[str, Connection] = {}
        self.client_conns: Dict[str, Connection] = {}  # drivers/workers subscribed
        self.actors: Dict[bytes, ActorRecord] = {}
        self.named_actors: Dict[tuple, bytes] = {}  # (namespace, name) -> actor_id
        self.jobs: Dict[bytes, dict] = {}
        self.kv: Dict[str, Dict[bytes, bytes]] = {}
        self.pgs: Dict[str, PlacementGroupRecord] = {}
        self.object_dir: Dict[bytes, Set[str]] = {}
        self.object_waiters: Dict[bytes, List[asyncio.Future]] = {}
        self.subscribers: Dict[str, Set[Connection]] = {}  # channel -> conns
        self._pub_buf: Dict[Connection, list] = {}  # batched pubsub outbox
        self._pub_flush: Optional[asyncio.Task] = None
        self._pg_lock = asyncio.Lock()
        # committed gang rings (topology.py): pg_id -> frozenset of torus
        # links its induced allreduce ring occupies; feeds the contention
        # score of every later placement + sched_ring_overlap_ratio
        self._pg_rings: Dict[str, frozenset] = {}
        self._sched_repacks = 0  # bundles migrated by the repack pass
        self._next_job = 1
        self._started = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self.task_events: List[dict] = []  # bounded task-event log for state API
        # structured cluster events (ray parity: src/ray/util/event.h:130 —
        # severity/source/label/message + custom fields), bounded ring
        self.events: deque = deque(maxlen=10_000)
        self._store = make_store(persist_path, cluster_id=cluster_id)
        # step observatory: rolling collective-skew fold (steptrace.py),
        # built lazily on the first steptrace_cluster scrape
        self._steptrace_agg = None
        # request observatory: rolling serve-request fold (reqtrace.py),
        # built lazily on the first reqtrace_cluster scrape
        self._reqtrace_agg = None
        self._recovering: Set[bytes] = set()  # actor_ids awaiting raylet reclaim
        self._recovered = self._replay()

    def _replay(self) -> bool:
        """Rebuild tables from the persistent store (ray: gcs_init_data.h —
        a restarted GCS loads all tables before serving)."""
        tables = self._store.load()
        if not tables:
            return False
        for (ns, key), value in tables.get("kv", {}).items():
            self.kv.setdefault(ns, {})[key] = value
        for job_id, job in tables.get("job", {}).items():
            self.jobs[job_id] = job
        self._next_job = tables.get("meta", {}).get("next_job", 1)
        for pg_id, d in tables.get("pg", {}).items():
            if d["state"] != "REMOVED":
                self.pgs[pg_id] = PlacementGroupRecord.restore(d)
        for actor_id, d in tables.get("actor", {}).items():
            rec = ActorRecord.restore(d)
            self.actors[actor_id] = rec
            if rec.name and rec.state != DEAD:
                self.named_actors[(rec.namespace, rec.name)] = actor_id
            if rec.state != DEAD:
                # Raylets reconnect and reclaim still-running actors; the
                # rest are failed over after the reconnect window.
                rec.state = RESTARTING
                self._recovering.add(actor_id)
        logger.info(
            "GCS restarted from store: %d actors (%d recovering), %d pgs, "
            "%d jobs", len(self.actors), len(self._recovering), len(self.pgs),
            len(self.jobs),
        )
        return True

    def _setup_metrics(self):
        """GCS runtime gauges (metrics_core.py): node liveness + control
        tables, evaluated at snapshot time. The remote-KV pipeline's
        queue/breaker gauges register in gcs_store.RemoteKvStore."""
        from ray_tpu._private import metrics_core as mc

        reg = mc.registry()
        nodes = reg.gauge("gcs_node_count", "Cluster nodes by liveness")
        nodes.labels(state="alive").set_fn(
            lambda: sum(1 for n in self.nodes.values() if n.alive))
        nodes.labels(state="dead").set_fn(
            lambda: sum(1 for n in self.nodes.values() if not n.alive))
        reg.gauge("gcs_actor_count", "Actor records in the GCS table"
                  ).set_fn(lambda: len(self.actors))
        reg.gauge("gcs_placement_group_count", "Placement group records"
                  ).set_fn(lambda: len(self.pgs))
        reg.gauge("gcs_subscriber_conns", "Pubsub subscriber connections"
                  ).set_fn(lambda: sum(len(s)
                                       for s in self.subscribers.values()))
        # gang-scheduler health: aggregate ring overlap across committed
        # gangs (0 = every gang owns its torus links) + repack activity
        reg.gauge(
            "sched_ring_overlap_ratio",
            "Pairwise shared torus links / total ring links across "
            "committed placement-group gangs",
        ).set_fn(self._ring_overlap_ratio)
        reg.counter(
            "sched_repack_total",
            "Pending placement-group bundles migrated by the "
            "fragmentation repack pass",
        ).set_fn(lambda: self._sched_repacks)

    def _ring_overlap_ratio(self) -> float:
        from ray_tpu._private import topology

        return topology.overlap_ratio(self._pg_rings)

    async def start(self):
        port = await self.server.start()
        faultsim.set_self_id(f"gcs:{port}")
        self._setup_metrics()
        self._tasks.append(spawn(self._health_loop()))
        if self._recovered:
            self._tasks.append(
                spawn(self._finish_recovery())
            )
        self._started.set()
        logger.info("GCS listening on %s", port)
        return port

    async def _finish_recovery(self):
        """After the failover window, restart recovering actors nobody
        reclaimed and re-place PGs whose nodes never came back (ray:
        gcs_failover_worker_reconnect_timeout, node_manager.proto:358
        NotifyGCSRestart — our raylets reconnect and re-register instead)."""
        await asyncio.sleep(cfg.gcs_failover_reconnect_timeout_s)
        for actor_id in list(self._recovering):
            self._recovering.discard(actor_id)
            rec = self.actors.get(actor_id)
            if rec is not None and rec.state == RESTARTING:
                await self._handle_actor_failure(
                    rec, "actor lost during GCS failover"
                )
        for pg in list(self.pgs.values()):
            if pg.state == "CREATED" and any(
                nid not in self.nodes or not self.nodes[nid].alive
                for nid in pg.bundle_nodes
            ):
                pg.state = "PENDING"
                pg.bundle_nodes = [None] * len(pg.bundles)
                self._reset_pg_provenance(pg)
                self._pg_rings.pop(pg.pg_id, None)
                self._persist_pg(pg)
                spawn(self._schedule_pg(pg))
        # Jobs whose driver never reconnected: treat the driver as dead (its
        # exit raced the GCS outage, so the disconnect cleanup never ran).
        live_jobs = {
            c.meta.get("job_id")
            for c in self.client_conns.values()
            if c.meta.get("is_driver")
        }
        for job_id, job in list(self.jobs.items()):
            if not job["is_dead"] and job_id not in live_jobs:
                await self._on_driver_exit(job_id)

    # -- persistence write-through helpers ------------------------------
    def _persist_actor(self, rec: ActorRecord):
        self._store.put("actor", rec.actor_id, rec.dump())

    def _persist_pg(self, pg: PlacementGroupRecord):
        self._store.put("pg", pg.pg_id, pg.dump())

    def _persist_job(self, job_id: bytes):
        self._store.put("job", job_id, self.jobs[job_id])

    async def stop(self):
        # drain the pubsub outbox first: publishes acked in the final tick
        # (e.g. node-dead from a teardown path) must still reach subscribers
        if self._pub_flush is not None:
            try:
                await asyncio.wait_for(
                    asyncio.shield(self._pub_flush), timeout=2.0
                )
            except Exception:
                pass
        for t in self._tasks:
            t.cancel()
        await self.server.stop()
        self._store.close()

    # ------------------------------------------------------------------
    # Connection lifecycle
    # ------------------------------------------------------------------
    def on_disconnect(self, conn: Connection):
        # drop pubsub subscriptions FIRST: the driver/raylet early
        # returns below used to skip this, leaving dead conns inflating
        # subscriber counts (the heartbeat-reported "logs" count gates
        # raylet log tailing, so a leak here would keep every raylet
        # tailing after the last driver exited)
        for subs in self.subscribers.values():
            subs.discard(conn)
        kind = conn.meta.get("kind")
        if kind == "raylet":
            node_id = conn.meta["node_id"]
            self.node_conns.pop(node_id, None)
            return self._mark_node_dead(node_id, "raylet disconnected")
        if kind == "client":
            self.client_conns.pop(conn.meta.get("client_id"), None)
            job_id = conn.meta.get("job_id")
            if conn.meta.get("is_driver") and job_id is not None:
                return self._on_driver_exit(job_id)

    async def _on_driver_exit(self, job_id: bytes):
        """Driver died/finished: finish job, destroy its non-detached actors."""
        job = self.jobs.get(job_id)
        if job:
            job["is_dead"] = True
            job["end_time"] = time.time()
            self._persist_job(job_id)
        for rec in list(self.actors.values()):
            if rec.spec.job_id == job_id and rec.spec.lifetime != "detached" \
                    and rec.state != DEAD:
                await self._destroy_actor(rec, "owner job finished")
        for pg in list(self.pgs.values()):
            if pg.job_id == job_id and pg.lifetime != "detached":
                await self._remove_pg(pg.pg_id)

    # ------------------------------------------------------------------
    # Node manager (+ health checks)
    # ------------------------------------------------------------------
    async def rpc_register_node(self, conn: Connection, info: dict):
        state = info.pop("state", None)
        node = NodeInfo(**info)
        node.resources_available = dict(node.resources_total)
        self.nodes[node.node_id] = node
        conn.meta.update(kind="raylet", node_id=node.node_id)
        self.node_conns[node.node_id] = conn
        if state:
            await self._reconcile_node_state(node.node_id, state)
        await self._publish("node", {"event": "alive", "node": info})
        self._record_event(
            "INFO", "gcs", "NODE_ADDED",
            f"node {node.node_id[:12]} joined at {node.host}:{node.port}",
            {"node_id": node.node_id},
        )
        await self._broadcast_view()
        # New capacity: placement groups that gave up as INFEASIBLE get
        # another scheduling run (the autoscaler may have just launched
        # the slice their bundles were waiting for).
        for pg in list(self.pgs.values()):
            if pg.state == "INFEASIBLE":
                pg.state = "PENDING"
                self._persist_pg(pg)
                spawn(self._schedule_pg(pg))
        return {"node_id": node.node_id, "nodes": self._view()}

    async def _reconcile_node_state(self, node_id: str, state: dict):
        """A raylet re-registered after a GCS restart (or its own reconnect)
        and reported what it is actually running; fold that back into the
        replayed tables (reference analog: RayletNotifyGCSRestart +
        per-table resubscription, core_worker.proto:417)."""
        for actor_id, client_id in state.get("actors_running", {}).items():
            rec = self.actors.get(actor_id)
            if rec is not None and rec.state != DEAD:
                rec.node_id = node_id
                rec.address = (node_id, client_id)
                # re-registered after GCS restart: the direct endpoint is
                # unknown here; drivers fall back to raylet routing
                rec.direct_addr = None
                rec.state = ALIVE
                self._recovering.discard(actor_id)
                await self._publish_actor(rec)
        for oid in state.get("objects", ()):
            self.object_dir.setdefault(oid, set()).add(node_id)
            for fut in self.object_waiters.pop(oid, []):
                if not fut.done():
                    fut.set_result([node_id])
        for pg_id, bundle_index in state.get("pg_bundles", ()):
            pg = self.pgs.get(pg_id)
            if pg is not None and pg.state == "CREATED" \
                    and 0 <= bundle_index < len(pg.bundle_nodes):
                pg.bundle_nodes[bundle_index] = node_id

    async def rpc_heartbeat(self, conn: Connection, payload: dict):
        node = self.nodes.get(payload["node_id"])
        if node is None:
            return {"reregister": True}
        node.last_heartbeat = time.monotonic()
        node.resources_available = payload["resources_available"]
        if "resources_total" in payload:
            node.resources_total = payload["resources_total"]
        node.pending_demand = payload.get("pending_demand", [])
        idle = payload.get("idle", False)
        if idle and not node.idle:
            node.idle_since = time.monotonic()
        node.idle = idle
        if not node.alive:
            node.alive = True
        # "logs"-channel subscriber count: raylets skip tailing worker
        # logs entirely while nobody is listening (log plane costs
        # nothing on an unwatched cluster)
        return {"log_subscribers": len(self.subscribers.get("logs", ()))}

    async def rpc_get_load_metrics(self, conn: Connection, _):
        """Autoscaler input: per-node demand + idle durations (ray:
        monitor.proto:100 GetAllResourceUsage)."""
        now = time.monotonic()
        nodes = []
        demand = []
        for n in self.nodes.values():
            if not n.alive:
                continue
            nodes.append({
                "node_id": n.node_id,
                "resources_total": n.resources_total,
                "resources_available": n.resources_available,
                "labels": n.labels,
                "idle_s": (now - n.idle_since) if n.idle else 0.0,
            })
            demand.extend(n.pending_demand)
        # Unschedulable actors are demand too (ray: GcsAutoscalerStateManager
        # folds pending actor creations into the load report).
        for rec in self.actors.values():
            if rec.state in (PENDING_CREATION, RESTARTING) and rec.spec.resources:
                demand.append(dict(rec.spec.resources))
        # Unplaced placement groups report every bundle (ray: the
        # autoscaler sees PG demand via placement_group_load) — this is
        # what makes pending TPU PGs launch whole slices.
        for pg in self.pgs.values():
            if pg.state in ("PENDING", "INFEASIBLE"):
                demand.extend(dict(b) for b in pg.bundles)
        return {"nodes": nodes, "pending_demand": demand}

    async def rpc_get_nodes(self, conn: Connection, _):
        return self._view()

    def _view(self):
        return [
            {
                "node_id": n.node_id,
                "host": n.host,
                "port": n.port,
                "store_dir": n.store_dir,
                "resources_total": n.resources_total,
                "resources_available": n.resources_available,
                "labels": n.labels,
                "alive": n.alive,
            }
            for n in self.nodes.values()
        ]

    async def _broadcast_view(self):
        view = self._view()
        for nid, conn in list(self.node_conns.items()):
            try:
                await conn.notify("cluster_view", view)
            except Exception:
                pass

    async def _health_loop(self):
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_heartbeat > cfg.node_death_timeout_s:
                    await self._mark_node_dead(node.node_id, "heartbeat timeout")
            await self._broadcast_view()

    async def _mark_node_dead(self, node_id: str, reason: str):
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return
        node.alive = False
        logger.warning("node %s marked dead: %s", node_id[:8], reason)
        self._record_event(
            "WARNING", "gcs", "NODE_DEAD",
            f"node {node_id[:12]} marked dead: {reason}",
            {"node_id": node_id, "reason": reason},
        )
        await self._publish("node", {"event": "dead", "node_id": node_id, "reason": reason})
        # Restart or fail actors that lived there.
        for rec in list(self.actors.values()):
            if rec.node_id == node_id and rec.state in (ALIVE, PENDING_CREATION):
                await self._handle_actor_failure(rec, f"node died: {reason}")
        await self._broadcast_view()

    # ------------------------------------------------------------------
    # Jobs
    # ------------------------------------------------------------------
    async def rpc_register_job(self, conn: Connection, payload: dict):
        job_num = self._next_job
        self._next_job += 1
        from ray_tpu._private.ids import JobID

        job_id = JobID.from_int(job_num).binary()
        self.jobs[job_id] = {
            "job_id": job_id,
            "start_time": time.time(),
            "is_dead": False,
            "driver": payload.get("driver", {}),
            "namespace": payload.get("namespace") or "default",
            "end_time": None,
        }
        self._persist_job(job_id)
        self._store.put("meta", "next_job", self._next_job)
        return {"job_id": job_id}

    async def rpc_register_client(self, conn: Connection, payload: dict):
        conn.meta.update(
            kind="client",
            client_id=payload["client_id"],
            job_id=payload.get("job_id"),
            is_driver=payload.get("is_driver", False),
        )
        self.client_conns[payload["client_id"]] = conn
        return {}

    async def rpc_list_jobs(self, conn: Connection, _):
        return list(self.jobs.values())

    # ------------------------------------------------------------------
    # Internal KV (ray: gcs_kv_manager.h)
    # ------------------------------------------------------------------
    async def _persist_kv_awaited(self, key, value):
        """Persist one user-visible KV mutation BEFORE the client sees
        the ack. Internal table writes (_persist_actor/_persist_pg) stay
        fire-and-forget — a slow store must not stall the control plane —
        but a kv_put the client observed succeeding has to survive a
        kill -9 of the GCS (the redis-store durability contract). Stores
        with an awaitable path (RemoteKvStore.aput) flush without
        blocking the event loop; local stores write synchronously (disk,
        microseconds). Returns False when the flush did NOT land (breaker
        open / put timeout) so the ack can say so."""
        aput = getattr(self._store, "aput", None)
        if aput is None:
            self._store.put("kv", key, value)
            return True
        return bool(await aput("kv", key, value))

    async def rpc_kv_put(self, conn: Connection, p):
        nsname = p.get("ns", "")
        ns = self.kv.setdefault(nsname, {})
        existed = p["key"] in ns
        persisted = True
        if p.get("overwrite", True) or not existed:
            ns[p["key"]] = p["value"]
            # volatile: rendezvous-lifetime data (collective chunk
            # payloads) that is useless after a GCS restart — the gang
            # re-forms its group and republishes (PR 17 recovery path).
            # Skipping the store write keeps multi-MB chunk streams off
            # the disk path entirely.
            if not p.get("volatile"):
                persisted = await self._persist_kv_awaited(
                    (nsname, p["key"]), p["value"])
        # persisted=False = the degraded no-persist posture: the write is
        # live in memory but would not survive a GCS kill -9 right now
        return {"added": not existed, "persisted": persisted}

    async def rpc_kv_get(self, conn: Connection, p):
        return self.kv.get(p.get("ns", ""), {}).get(p["key"])

    async def rpc_kv_del(self, conn: Connection, p):
        nsname = p.get("ns", "")
        ns = self.kv.get(nsname, {})
        if p.get("prefix"):
            keys = [k for k in ns if k.startswith(p["key"])]
            deleted = 0
            for k in keys:
                # pop, not del: the await below suspends the handler, so
                # a concurrent kv_del may have removed (and tombstoned)
                # this key already
                if ns.pop(k, None) is None:
                    continue
                deleted += 1
                await self._persist_kv_awaited((nsname, k), None)
            return deleted
        if ns.pop(p["key"], None) is not None:
            await self._persist_kv_awaited((nsname, p["key"]), None)
            return 1
        return 0

    async def rpc_kv_keys(self, conn: Connection, p):
        ns = self.kv.get(p.get("ns", ""), {})
        return [k for k in ns if k.startswith(p.get("prefix", b""))]

    async def rpc_kv_exists(self, conn: Connection, p):
        return p["key"] in self.kv.get(p.get("ns", ""), {})

    # ------------------------------------------------------------------
    # Pubsub (ray: src/ray/pubsub/)
    # ------------------------------------------------------------------
    # -- structured events (ray parity: util/event.h + event aggregator) --
    def _record_event(self, severity: str, source: str, label: str,
                      message: str, fields: Optional[dict] = None):
        self.events.append({
            "timestamp": time.time(),
            "severity": severity,
            "source": source,
            "label": label,
            "message": message,
            "fields": fields or {},
        })

    async def rpc_add_event(self, conn: Connection, p):
        self._record_event(
            p.get("severity", "INFO"), p.get("source", "user"),
            p.get("label", ""), p.get("message", ""), p.get("fields"),
        )
        return {}

    async def rpc_get_events(self, conn: Connection, p):
        severity = p.get("severity")
        source = p.get("source")
        limit = p.get("limit") or 100
        out = []
        for ev in reversed(self.events):  # newest first
            if severity and ev["severity"] != severity:
                continue
            if source and ev["source"] != source:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        return out

    async def rpc_subscribe(self, conn: Connection, p):
        self.subscribers.setdefault(p["channel"], set()).add(conn)
        return {}

    async def rpc_publish(self, conn: Connection, p):
        await self._publish(p["channel"], p["message"])
        return {}

    async def _publish(self, channel: str, message):
        """Queue the message per subscriber and flush in batches.

        The reference batches pubsub delivery (ray: src/ray/pubsub/ — the
        long-poll reply carries every message queued since the last poll).
        Same effect here on duplex connections: messages published in the
        same loop tick coalesce into one "pubsub_batch" notify per
        subscriber, so a burst of table updates (actor churn, PG commits)
        costs one frame per peer instead of one per message.
        """
        for conn in list(self.subscribers.get(channel, ())):
            if conn.closed:
                self.subscribers[channel].discard(conn)
                continue
            self._pub_buf.setdefault(conn, []).append((channel, message))
        if self._pub_buf and self._pub_flush is None:
            self._pub_flush = spawn(
                self._flush_pubsub()
            )

    async def _flush_pubsub(self):
        try:
            # one loop turn lets same-tick publishes pile into the batch
            await asyncio.sleep(0)
            while self._pub_buf:
                buf, self._pub_buf = self._pub_buf, {}
                for conn, batch in buf.items():
                    if conn.closed:
                        continue
                    try:
                        await conn.notify("pubsub_batch", {"batch": batch})
                    except Exception:
                        pass
        finally:
            # reset even if cancelled mid-await so later publishes can
            # schedule a fresh flush
            self._pub_flush = None

    # ------------------------------------------------------------------
    # Object directory (centralized variant of the ownership directory)
    # ------------------------------------------------------------------
    async def rpc_add_object_location(self, conn: Connection, p):
        oid, node_id = p["object_id"], p["node_id"]
        self.object_dir.setdefault(oid, set()).add(node_id)
        waiters = self.object_waiters.pop(oid, [])
        for fut in waiters:
            if not fut.done():
                fut.set_result([node_id])
        return {}

    async def rpc_add_object_locations(self, conn: Connection, p):
        """Batched variant: one frame per slab-accounting burst (the
        arena's batched put path registers many objects per tick)."""
        node_id = p["node_id"]
        for oid in p["object_ids"]:
            self.object_dir.setdefault(oid, set()).add(node_id)
            for fut in self.object_waiters.pop(oid, []):
                if not fut.done():
                    fut.set_result([node_id])
        return {}

    async def rpc_remove_object_location(self, conn: Connection, p):
        locs = self.object_dir.get(p["object_id"])
        if locs:
            locs.discard(p["node_id"])
            if not locs:
                del self.object_dir[p["object_id"]]
        return {}

    async def rpc_get_object_locations(self, conn: Connection, p):
        locs = self.object_dir.get(p["object_id"], set())
        live = [nid for nid in locs if self.nodes.get(nid) and self.nodes[nid].alive]
        if live or not p.get("wait"):
            return live
        fut = asyncio.get_running_loop().create_future()
        self.object_waiters.setdefault(p["object_id"], []).append(fut)
        try:
            return await asyncio.wait_for(fut, p.get("timeout", cfg.object_pull_timeout_s))
        except asyncio.TimeoutError:
            return []

    async def rpc_free_object(self, conn: Connection, p):
        """Owner released the object: tell all holding raylets to delete it."""
        await self._free_objects([p["object_id"]])
        return {}

    async def rpc_free_objects(self, conn: Connection, p):
        """Batched variant: one frame for a release burst (a 10k-object
        teardown as 10k serial RPCs would wedge the raylet loop for
        seconds and starve every free queued behind it)."""
        await self._free_objects(p["object_ids"])
        return {}

    async def _free_objects(self, oids):
        per_node: Dict[bytes, list] = {}
        for oid in oids:
            for nid in self.object_dir.pop(oid, set()):
                per_node.setdefault(nid, []).append(oid)
        for nid, node_oids in per_node.items():
            nconn = self.node_conns.get(nid)
            if nconn:
                try:
                    await nconn.notify(
                        "delete_objects", {"object_ids": node_oids}
                    )
                except Exception:
                    pass

    # ------------------------------------------------------------------
    # Actor manager + scheduler (ray: gcs_actor_manager.h, gcs_actor_scheduler.h)
    # ------------------------------------------------------------------
    async def rpc_register_actor(self, conn: Connection, p):
        spec: TaskSpec = p["spec"]
        rec = ActorRecord(spec)
        rec.owner_conn_key = conn.meta.get("client_id")
        if rec.name:
            key = (rec.namespace, rec.name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing and existing.state != DEAD:
                    return {"error": f"actor name '{rec.name}' already taken"}
            self.named_actors[key] = rec.actor_id
        self.actors[rec.actor_id] = rec
        self._persist_actor(rec)
        spawn(self._schedule_actor(rec))
        return {"actor_id": rec.actor_id}

    async def _schedule_actor(self, rec: ActorRecord):
        # Per-actor scheduling loop; no global lock — concurrent creations
        # race on node resources and rely on raylet-side admission (rejects)
        # plus retry, like the reference's per-actor GcsActorScheduler.
        if rec.state == DEAD:
            return
        rec.state = PENDING_CREATION
        await self._publish_actor(rec)
        spec = rec.spec
        from ray_tpu._private.common import SchedulingStrategy, pick_node

        demand = dict(spec.resources)
        strategy = spec.scheduling or SchedulingStrategy()
        deadline = time.monotonic() + cfg.worker_lease_timeout_ms / 1000.0
        rr = [0]
        while time.monotonic() < deadline:
            if rec.state == DEAD:
                return
            nodes = [n for n in self.nodes.values() if n.alive]
            target = pick_node(nodes, demand, strategy, None, rr,
                               cfg.scheduler_spread_threshold)
            if target is None or self.node_conns.get(target) is None:
                await asyncio.sleep(cfg.gcs_schedule_retry_interval_s)
                continue
            try:
                # No rpc idem token: the scheduling loop legitimately
                # re-asks the same node after a transient rejection, and a
                # token would replay the cached rejection forever. Lost-
                # reply dedup lives in the raylet instead — rpc_create_actor
                # re-answers for an actor_id it already runs.
                reply = await self.node_conns[target].request(
                    "create_actor", {"spec": spec},
                    timeout=cfg.gcs_rpc_timeout_s,
                )
            except Exception as e:
                logger.warning("actor creation on %s failed: %s", target[:8], e)
                await asyncio.sleep(cfg.gcs_schedule_retry_interval_s)
                continue
            if reply.get("rejected"):
                await asyncio.sleep(0.1)
                continue
            if reply.get("error"):
                rec.state = DEAD
                rec.death_cause = reply["error"]
                await self._publish_actor(rec)
                return
            rec.node_id = target
            rec.address = (target, reply["worker_client_id"])
            rec.direct_addr = tuple(reply["direct_addr"]) if reply.get("direct_addr") else None
            rec.state = ALIVE
            await self._publish_actor(rec)
            return
        rec.state = DEAD
        rec.death_cause = "actor creation timed out (no feasible node)"
        await self._publish_actor(rec)

    async def _publish_actor(self, rec: ActorRecord):
        self._persist_actor(rec)
        await self._publish("actor", rec.to_table())

    async def rpc_get_actor(self, conn: Connection, p):
        rec = None
        if p.get("actor_id"):
            rec = self.actors.get(p["actor_id"])
        elif p.get("name"):
            aid = self.named_actors.get((p.get("namespace") or "default", p["name"]))
            rec = self.actors.get(aid) if aid else None
            if rec and rec.state == DEAD:
                rec = None
        return rec.to_table() if rec else None

    async def rpc_list_actors(self, conn: Connection, _):
        return [r.to_table() for r in self.actors.values()]

    async def rpc_wait_actor_alive(self, conn: Connection, p):
        """Block until the actor is ALIVE or DEAD; returns its table entry.

        An unknown actor_id is awaited too (not failed immediately): the
        registration may legitimately trail task submission when the actor's
        creation arguments are still being resolved by the owner."""
        deadline = time.monotonic() + p.get("timeout", cfg.gcs_rpc_timeout_s)
        while time.monotonic() < deadline:
            rec = self.actors.get(p["actor_id"])
            if rec is not None and rec.state in (ALIVE, DEAD):
                return rec.to_table()
            await asyncio.sleep(0.02)
        rec = self.actors.get(p["actor_id"])
        return rec.to_table() if rec else None

    async def rpc_actor_died(self, conn: Connection, p):
        """Raylet reports an actor worker exited."""
        rec = self.actors.get(p["actor_id"])
        if rec is None or rec.state == DEAD:
            return {}
        if p.get("intended"):
            await self._destroy_actor(rec, p.get("reason", "killed"))
        else:
            await self._handle_actor_failure(rec, p.get("reason", "worker died"))
        return {}

    async def _handle_actor_failure(self, rec: ActorRecord, reason: str):
        max_restarts = rec.spec.max_restarts
        will_restart = max_restarts == -1 or rec.num_restarts < max_restarts
        self._record_event(
            "WARNING" if will_restart else "ERROR", "gcs",
            "ACTOR_RESTARTING" if will_restart else "ACTOR_DEAD",
            f"actor {rec.actor_id.hex()[:12]} ({rec.spec.name}) failed: "
            f"{reason}" + (" — restarting" if will_restart else ""),
            {"actor_id": rec.actor_id.hex(), "reason": reason},
        )
        if will_restart:
            rec.num_restarts += 1
            rec.state = RESTARTING
            rec.node_id = None
            rec.address = None
            rec.direct_addr = None
            await self._publish_actor(rec)
            await asyncio.sleep(cfg.actor_restart_delay_ms / 1000.0)
            spawn(self._schedule_actor(rec))
        else:
            await self._destroy_actor(rec, reason)

    async def _destroy_actor(self, rec: ActorRecord, reason: str):
        rec.state = DEAD
        rec.death_cause = reason
        if rec.name:
            self.named_actors.pop((rec.namespace, rec.name), None)
        if rec.node_id and rec.address:
            nconn = self.node_conns.get(rec.node_id)
            if nconn:
                try:
                    await nconn.notify(
                        "kill_actor", {"actor_id": rec.actor_id, "no_restart": True}
                    )
                except Exception:
                    pass
        await self._publish_actor(rec)

    async def rpc_kill_actor(self, conn: Connection, p):
        rec = self.actors.get(p["actor_id"])
        if rec is None:
            return {}
        if p.get("no_restart", True):
            await self._destroy_actor(rec, "ray.kill")
        else:
            await self._handle_actor_failure(rec, "ray.kill(no_restart=False)")
        return {}

    # ------------------------------------------------------------------
    # Placement groups (ray: gcs_placement_group_manager.h — 2-phase commit)
    # ------------------------------------------------------------------
    async def rpc_create_placement_group(self, conn: Connection, p):
        pg = PlacementGroupRecord(
            p["pg_id"], p["bundles"], p["strategy"], p.get("name", ""),
            p.get("job_id"), p.get("lifetime"),
        )
        self.pgs[pg.pg_id] = pg
        self._persist_pg(pg)
        spawn(self._schedule_pg(pg))
        return {"pg_id": pg.pg_id}

    async def _schedule_pg(self, pg: PlacementGroupRecord):
        deadline = time.monotonic() + cfg.worker_lease_timeout_ms / 1000.0
        while pg.state == "PENDING" and time.monotonic() < deadline:
            placed = await self._try_place_pg(pg)
            if placed:
                return
            await asyncio.sleep(cfg.gcs_schedule_retry_interval_s)
        if pg.state == "PENDING":
            pg.state = "INFEASIBLE"
            self._persist_pg(pg)
            await self._publish("pg", pg.to_table())

    def _committed_rings(self, but: Optional[str] = None,
                         topo=None) -> dict:
        """Rings of committed gangs, excluding ``but`` (a re-placed PG
        must not contend against its own stale ring). Rings missing from
        the registry (a restarted GCS replays pg tables but not rings)
        are rebuilt from the replayed bundle_nodes when a topology is at
        hand."""
        if topo is not None:
            for pg in self.pgs.values():
                if (pg.state == "CREATED" and pg.pg_id != but
                        and pg.pg_id not in self._pg_rings):
                    self._pg_rings[pg.pg_id] = topo.ring_links(
                        [n for n in pg.bundle_nodes if n])
        return {
            pg_id: ring for pg_id, ring in self._pg_rings.items()
            if pg_id != but
            and (p := self.pgs.get(pg_id)) is not None
            and p.state == "CREATED"
        }

    def _idle_bundles(self, but: str) -> list:
        """Committed bundles with zero consumption — PENDING in the sense
        that nothing runs against their reserved resources yet, so they
        are safe to migrate. The GCS already sees this through the
        heartbeat view: a bundle's pg-formatted resources sit at full
        availability on its host iff no task/actor has claimed any of
        them. Rows: (pg_id, bundle_index, node_id, original_resources)."""
        from ray_tpu._private.common import (RESOURCE_QUANT,
                                             rewrite_resources_for_pg)

        rows = []
        for pg in self.pgs.values():
            if pg.pg_id == but or pg.state != "CREATED":
                continue
            for idx, node_id in enumerate(pg.bundle_nodes):
                node = self.nodes.get(node_id) if node_id else None
                if node is None or not node.alive:
                    continue
                named = rewrite_resources_for_pg(
                    pg.bundles[idx], pg.pg_id, idx)
                if all(abs(node.resources_available.get(k, 0.0) - v)
                       < RESOURCE_QUANT / 2 for k, v in named.items()):
                    rows.append((pg.pg_id, idx, node_id,
                                 dict(pg.bundles[idx])))
        return rows

    async def _prepare_and_commit(self, pg_id: str, placements: list,
                                  bundles: list) -> bool:
        """2-phase reserve: prepare every (idx, node) row, cancel all on
        any failure, else commit all. ``placements`` is [(idx, node_id)]."""
        prepared = []
        ok = True
        for idx, node_id in placements:
            nconn = self.node_conns.get(node_id)
            if nconn is None:
                ok = False
                break
            try:
                # no rpc idem token: prepare/cancel cycles across
                # placement attempts would replay stale results.
                # Dedup is app-level — rpc_pg_prepare acks a bundle
                # it already holds without double-reserving.
                r = await nconn.request(
                    "pg_prepare",
                    {"pg_id": pg_id, "bundle_index": idx,
                     "resources": bundles[idx]},
                    timeout=cfg.gcs_rpc_timeout_s,
                )
            except Exception:
                ok = False
                break
            if not r.get("ok"):
                ok = False
                break
            prepared.append((idx, node_id))
        if not ok:
            for idx, node_id in prepared:
                nconn = self.node_conns.get(node_id)
                if nconn:
                    try:
                        await nconn.notify(
                            "pg_cancel",
                            {"pg_id": pg_id, "bundle_index": idx})
                    except Exception:
                        pass
            return False
        for idx, node_id in prepared:
            nconn = self.node_conns.get(node_id)
            try:
                if nconn is None:  # raylet died between prepare and commit
                    raise ConnectionError(f"raylet {node_id[:12]} gone")
                await nconn.request(
                    "pg_commit", {"pg_id": pg_id, "bundle_index": idx},
                    timeout=cfg.gcs_rpc_timeout_s,
                )
            except Exception:
                # roll every reservation back (committed or not —
                # pg_cancel pops the bundle either way) instead of
                # crashing the scheduling task and stranding the PG
                for i2, n2 in prepared:
                    c2 = self.node_conns.get(n2)
                    if c2:
                        try:
                            await c2.notify(
                                "pg_cancel",
                                {"pg_id": pg_id, "bundle_index": i2})
                        except Exception:
                            pass
                return False
        return True

    def _reset_pg_provenance(self, pg: PlacementGroupRecord):
        pg.node_coords = [None] * len(pg.bundles)
        pg.contention_score = None
        pg.sched_strategy = "resource-fit"
        pg.repack_moves = 0

    async def _requeue_pg(self, pg: PlacementGroupRecord):
        """A repack failure left this PG's reservations in doubt: return
        every bundle (best effort, idempotent raylet-side), reset the
        record to PENDING, and reschedule from scratch — a CREATED row
        pointing at a reservation no raylet holds would strand every
        actor targeting it as infeasible forever."""
        for idx, node_id in enumerate(pg.bundle_nodes):
            nconn = self.node_conns.get(node_id) if node_id else None
            if nconn:
                try:
                    await nconn.notify(
                        "pg_return",
                        {"pg_id": pg.pg_id, "bundle_index": idx})
                except Exception:
                    pass
        pg.state = "PENDING"
        pg.bundle_nodes = [None] * len(pg.bundles)
        self._reset_pg_provenance(pg)
        self._pg_rings.pop(pg.pg_id, None)
        self._persist_pg(pg)
        await self._publish("pg", pg.to_table())
        spawn(self._schedule_pg(pg))

    async def _execute_repack(self, moves: list, topo) -> bool:
        """Apply a repack plan (topology.plan_repack): migrate each idle
        bundle return->prepare->commit, updating its PG's table row and
        ring. A failed target prepare re-prepares on the origin (best
        effort); if even that fails — or the conditional release's fate
        is unknown (rpc error) — the victim PG is requeued for a fresh
        placement rather than left CREATED with a phantom reservation."""
        for mv in moves:
            src = self.node_conns.get(mv.from_node)
            dst = self.node_conns.get(mv.to_node)
            victim = self.pgs.get(mv.pg_id)
            if dst is None or src is None:
                return False
            try:
                # conditional release: the raylet is the authority on
                # whether the bundle is still idle — our heartbeat view
                # can be a beat stale, and a bundle a fresh actor just
                # claimed must not be migrated out from under it
                r = await src.request(
                    "pg_return_if_idle",
                    {"pg_id": mv.pg_id, "bundle_index": mv.bundle_index},
                    timeout=cfg.gcs_rpc_timeout_s)
            except Exception:
                # ambiguous: the raylet may have released before the rpc
                # failed — reconcile by re-placing the victim entirely
                if victim is not None:
                    await self._requeue_pg(victim)
                return False
            if not r.get("ok"):
                return False
            ok = await self._prepare_and_commit(
                mv.pg_id, [(mv.bundle_index, mv.to_node)],
                {mv.bundle_index: mv.resources})
            if not ok:
                restored = await self._prepare_and_commit(
                    mv.pg_id, [(mv.bundle_index, mv.from_node)],
                    {mv.bundle_index: mv.resources})
                if not restored and victim is not None:
                    await self._requeue_pg(victim)
                return False
            moved_pg = self.pgs.get(mv.pg_id)
            if moved_pg is not None:
                moved_pg.bundle_nodes[mv.bundle_index] = mv.to_node
                moved_pg.repack_moves += 1
                if topo is not None:
                    from ray_tpu._private import topology as topo_mod

                    coord = topo.coords.get(mv.to_node)
                    moved_pg.node_coords[mv.bundle_index] = (
                        topo_mod.format_coord(coord)
                        if coord is not None else None)
                    self._pg_rings[mv.pg_id] = topo.ring_links(
                        [n for n in moved_pg.bundle_nodes if n])
                self._persist_pg(moved_pg)
                await self._publish("pg", moved_pg.to_table())
            self._sched_repacks += 1
            self._record_event(
                "INFO", "gcs", "PG_REPACK",
                f"migrated bundle {mv.bundle_index} of pg "
                f"{mv.pg_id[:12]} {mv.from_node[:12]} -> "
                f"{mv.to_node[:12]} (defragmentation)",
                {"pg_id": mv.pg_id, "bundle_index": mv.bundle_index,
                 "from_node": mv.from_node, "to_node": mv.to_node},
            )
        return True

    async def _try_place_pg(self, pg: PlacementGroupRecord) -> bool:
        from ray_tpu._private import topology as topo_mod

        # The lock covers one atomic place+prepare+commit attempt so two PGs
        # don't interleave reservations; waiting happens outside it.
        async with self._pg_lock:
            nodes = [n for n in self.nodes.values() if n.alive]
            topo = (topo_mod.Topology.from_nodes(nodes)
                    if cfg.sched_topology_enabled else None)
            committed = self._committed_rings(but=pg.pg_id, topo=topo)
            # one dispatch point for both worlds: the wrapper takes the
            # contention path when a topology is passed and the untouched
            # native/py resource-fit path otherwise
            placement = place_bundles(nodes, pg.bundles, pg.strategy,
                                      topology=topo,
                                      committed_rings=committed)
            moves: list = []
            if placement is None and topo is not None \
                    and pg.strategy == "STRICT_SPREAD":
                # fragmentation repack: migrate committed-but-unused
                # bundles of other gangs to open enough distinct nodes.
                # Topology-gated on purpose — the degrade contract says a
                # coord-less cluster behaves byte-identically to the old
                # resource-fit path, which never migrated anything.
                plan = topo_mod.plan_repack(
                    nodes, pg.bundles, pg.strategy,
                    self._idle_bundles(but=pg.pg_id),
                    max_moves=cfg.sched_repack_max_moves)
                if plan is not None:
                    placement, moves = plan
            if placement is None:
                return False
            if moves and not await self._execute_repack(moves, topo):
                return False
            if not await self._prepare_and_commit(
                    pg.pg_id, list(enumerate(placement)), pg.bundles):
                return False
            pg.bundle_nodes = list(placement)
            pg.state = "CREATED"
            pg.repack_moves = len(moves)
            if topo is not None:
                pg.node_coords = [
                    topo_mod.format_coord(topo.coords[nid])
                    if nid in topo.coords else None
                    for nid in placement
                ]
                self._pg_rings[pg.pg_id] = topo.ring_links(placement)
                if moves:
                    # the repack rewrote other gangs' rings: score against
                    # the CURRENT registry, not the pre-repack snapshot,
                    # and label the provenance honestly (plan_repack
                    # places by resource fit, not contention)
                    committed = self._committed_rings(but=pg.pg_id)
                score = topo.score(placement, committed)
                pg.contention_score = float(score.contention)
                pg.sched_strategy = ("topology-repack" if moves
                                     else "topology-contention")
            else:
                pg.node_coords = [None] * len(placement)
                pg.contention_score = None
                pg.sched_strategy = "resource-fit"
            self._persist_pg(pg)
            await self._publish("pg", pg.to_table())
            return True

    async def rpc_wait_placement_group(self, conn: Connection, p):
        deadline = time.monotonic() + p.get("timeout", cfg.gcs_rpc_timeout_s)
        while time.monotonic() < deadline:
            pg = self.pgs.get(p["pg_id"])
            if pg is None:
                return None
            # INFEASIBLE is NOT terminal: the autoscaler may be
            # provisioning the slice right now, and node registration
            # flips the PG back to PENDING — so waiters keep waiting.
            if pg.state in ("CREATED", "REMOVED"):
                return pg.to_table()
            await asyncio.sleep(0.02)
        pg = self.pgs.get(p["pg_id"])
        return pg.to_table() if pg else None

    async def rpc_remove_placement_group(self, conn: Connection, p):
        await self._remove_pg(p["pg_id"])
        return {}

    async def _remove_pg(self, pg_id: str):
        pg = self.pgs.get(pg_id)
        if pg is None or pg.state == "REMOVED":
            return
        for idx, node_id in enumerate(pg.bundle_nodes):
            if node_id is None:
                continue
            nconn = self.node_conns.get(node_id)
            if nconn:
                try:
                    await nconn.notify("pg_return", {"pg_id": pg_id, "bundle_index": idx})
                except Exception:
                    pass
        pg.state = "REMOVED"
        self._pg_rings.pop(pg_id, None)
        self._persist_pg(pg)
        await self._publish("pg", pg.to_table())

    async def rpc_pg_table(self, conn: Connection, p):
        if p and p.get("pg_id"):
            pg = self.pgs.get(p["pg_id"])
            return pg.to_table() if pg else None
        return [pg.to_table() for pg in self.pgs.values()]

    # ------------------------------------------------------------------
    # On-demand profiling (profiler.py): cluster-wide fan-out + merge
    # ------------------------------------------------------------------
    def _profiler(self):
        svc = getattr(self, "_profiler_svc", None)
        if svc is None:
            from ray_tpu._private import profiler

            svc = self._profiler_svc = profiler.ProfilerService(role="gcs")
        return svc

    async def rpc_profile_start(self, conn: Connection, p):
        return self._profiler().start(p or {})

    async def rpc_profile_stop(self, conn: Connection, p):
        return self._profiler().stop(p or {})

    async def rpc_profile_status(self, conn: Connection, p):
        return self._profiler().status()

    async def rpc_profile_cluster(self, conn: Connection, p):
        """Fan one profiling window out to every (or one) node's raylet —
        which fans out to its workers — and merge the results: summed
        collapsed stacks (cpu) or summed per-site deltas (mem), plus the
        per-process results for slicing (ray parity: the dashboard's
        per-pid py-spy attach, lifted to one cluster-wide operation)."""
        from ray_tpu._private import profiler
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        p = dict(p or {})
        kind = p.get("kind", "cpu")
        duration = min(float(p.get("duration") or 5.0),
                       cfg.profiler_max_duration_s)
        p["duration"] = duration
        node_filter = p.get("node_id")
        targets = []
        for nid, nconn in list(self.node_conns.items()):
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                continue
            if node_filter and not nid.startswith(node_filter):
                continue
            targets.append((nid, nconn))

        async def one(nid: str, nconn: Connection):
            try:
                reply = await nconn.request(
                    "profile_node", p, timeout=duration + 45.0
                )
                return reply.get("processes") or []
            except Exception as e:
                return [{"node_id": nid,
                         "error": f"{type(e).__name__}: {e}"}]

        jobs = [one(nid, nconn) for nid, nconn in targets]
        if p.get("include_gcs") and not node_filter:
            async def self_prof():
                out = await self._profiler().run(p)
                return [out]

            jobs.append(self_prof())
        per_node = await asyncio.gather(*jobs)
        processes = [proc for node_list in per_node for proc in node_list]
        merged = profiler.merge_profiles(processes, kind=kind)
        merged["duration_s"] = duration
        merged["nodes"] = len(targets)
        return merged

    # ------------------------------------------------------------------
    # Metrics plane (metrics_core.py): cluster-wide scrape fan-out+merge
    # ------------------------------------------------------------------
    async def rpc_metrics_snapshot(self, conn: Connection, p):
        from ray_tpu._private import metrics_core

        return metrics_core.process_snapshot("gcs")

    async def _scrape_processes(self, node_method: str, driver_method: str,
                                timeout: float, tag_drivers: bool = False):
        """Shared cluster-scrape fan-out (metrics_cluster and
        steptrace_cluster differ only in verb names + post-processing):
        every live raylet's node verb (which fans to its workers) plus
        every registered DRIVER connection's snapshot verb, gathered
        concurrently, unreachable targets folded to error dicts. Returns
        ``(processes, n_nodes)``."""

        async def node(nid: str, nconn: Connection):
            try:
                reply = await nconn.request(node_method, {},
                                            timeout=timeout)
                return reply.get("processes") or []
            except Exception as e:
                return [{"node_id": nid,
                         "error": f"{type(e).__name__}: {e}"}]

        async def driver(cid: str, cconn: Connection):
            try:
                out = await cconn.request(driver_method, {},
                                          timeout=timeout)
                if tag_drivers:
                    out.setdefault("node_id", f"driver:{cid}")
                return [out]
            except Exception as e:
                return [{"client_id": cid,
                         "error": f"{type(e).__name__}: {e}"}]

        jobs = []
        n_nodes = 0
        for nid, nconn in list(self.node_conns.items()):
            info = self.nodes.get(nid)
            if info is None or not info.alive:
                continue
            n_nodes += 1
            jobs.append(node(nid, nconn))
        for cid, cconn in list(self.client_conns.items()):
            if cconn.meta.get("is_driver") and not cconn.closed:
                jobs.append(driver(cid, cconn))
        per = await asyncio.gather(*jobs)
        return [proc for plist in per for proc in plist], n_nodes

    async def rpc_metrics_cluster(self, conn: Connection, p):
        """One cluster-wide scrape: fan to every live raylet (which fans
        to its workers), every registered DRIVER connection (user metrics
        live in driver processes; workers are already covered through
        their raylet), plus this GCS — then merge (sum counters/gauges,
        merge histogram buckets). Mirrors profile_cluster's shape, but
        cheap enough to poll: one snapshot is a dict copy per process,
        no sampling window."""
        from ray_tpu._private import metrics_core

        processes, n_nodes = await self._scrape_processes(
            "metrics_node", "metrics_snapshot",
            cfg.metrics_scrape_timeout_s)
        processes.append(metrics_core.process_snapshot("gcs"))
        ok = [proc for proc in processes if not proc.get("error")]
        merged = metrics_core.merge_snapshots(
            [proc.get("metrics") or {} for proc in ok])
        return {
            "merged": merged,
            "processes": processes,
            "nodes": n_nodes,
            "record_calls": sum(proc.get("record_calls", 0) for proc in ok),
            "errors": [proc for proc in processes if proc.get("error")],
        }

    # ------------------------------------------------------------------
    # Step observatory (steptrace.py): per-step/per-collective telemetry
    # fan-out + (group, seq) arrival-skew merge
    # ------------------------------------------------------------------
    async def rpc_steptrace_cluster(self, conn: Connection, p):
        """One cluster-wide step-telemetry scrape: fan to every live
        raylet (which fans to its workers) plus registered DRIVER
        connections (a driver can be a collective rank too), then

        1. fold the NEW collective records into the rolling skew metrics
           (``collective_skew_seconds{rank=}`` histograms + per-rank
           ``steptrace_straggler_score`` gauge) — they live in THIS
           process's registry, so they ride the existing /metrics
           cluster scrape with no extra plumbing;
        2. join per-rank records by (group, seq) into the merged
           multi-rank view the train timeline renders.

        Mirrors metrics_cluster's shape; the fold is idempotent across
        repeated scrapes (per-process record indices high-water-mark)."""
        from ray_tpu._private import steptrace

        processes, _ = await self._scrape_processes(
            "steptrace_node", "steptrace_snapshot",
            cfg.steptrace_scrape_timeout_s, tag_drivers=True)
        agg = self._steptrace_agg
        if agg is None:
            agg = self._steptrace_agg = steptrace.SkewAggregator()
        # The merge runs over the aggregator's ACCUMULATED log, not just
        # this scrape: the timeline must survive the workers that
        # produced it (a trainer's shutdown scrape drains the gang's
        # rings here right before the actors die). fold + log copy +
        # merge are all CPU-bound python over up to log_limit records —
        # the whole thing runs on an executor thread (the aggregator is
        # internally locked) so a full log never stalls the GCS event
        # loop; ?limit caps the merge to the newest N records for cheap
        # polling surfaces.
        merged = await asyncio.get_running_loop().run_in_executor(
            None, agg.fold_and_merge, processes,
            (p or {}).get("limit") or 0)
        merged["processes"] = len(processes)
        merged["errors"] = [proc for proc in processes
                            if proc.get("error")]
        return merged

    # ------------------------------------------------------------------
    # Request observatory (reqtrace.py): per-request serve tracing
    # fan-out + request-id join into phase breakdowns and skew verdicts
    # ------------------------------------------------------------------
    async def rpc_reqtrace_cluster(self, conn: Connection, p):
        """One cluster-wide serve request-trace scrape: fan to every
        live raylet (serve proxies and replicas are actors in worker
        processes) plus registered DRIVER connections (handle-direct
        callers record route spans driver-side), then

        1. fold the NEW spans into the rolling request metrics
           (``serve_request_phase_seconds{app,deployment,phase}`` +
           ``serve_request_ttft_seconds``) — they live in THIS process's
           registry, so they ride the existing /metrics cluster scrape;
        2. join proxy+replica records by request id into per-request
           phase breakdowns, per-deployment p50/p95/p99, per-replica
           phase profiles, and slow-replica skew verdicts.

        The merge runs over the aggregator's ACCUMULATED log, not just
        this scrape — the request timeline survives the proxies/replicas
        that produced it. Mirrors steptrace_cluster's posture: the fold
        is idempotent across repeated scrapes (per-process record-index
        high-water marks) and the CPU-bound fold+merge runs on an
        executor thread; ?limit caps the merge for polling surfaces."""
        from ray_tpu._private import reqtrace

        processes, _ = await self._scrape_processes(
            "reqtrace_node", "reqtrace_snapshot",
            cfg.reqtrace_scrape_timeout_s, tag_drivers=True)
        agg = self._reqtrace_agg
        if agg is None:
            agg = self._reqtrace_agg = reqtrace.RequestAggregator()
        merged = await asyncio.get_running_loop().run_in_executor(
            None, agg.fold_and_merge, processes,
            (p or {}).get("limit") or 0)
        ok = [proc for proc in processes if not proc.get("error")]
        merged["processes"] = len(processes)
        merged["dropped"] = sum(proc.get("dropped", 0) for proc in ok)
        # cluster-wide record-attempt count: the overhead bench lane's
        # zero-records-when-disabled gate reads this
        merged["record_calls"] = sum(proc.get("record_calls", 0)
                                     for proc in ok)
        merged["errors"] = [proc for proc in processes
                            if proc.get("error")]
        return merged

    # ------------------------------------------------------------------
    # Memory observatory (memview.py): object lifecycle + arena
    # introspection fan-out, joined into leak/pressure verdicts
    # ------------------------------------------------------------------
    async def rpc_memview_cluster(self, conn: Connection, p):
        """One cluster-wide object-plane scrape: fan to every live
        raylet (store ledger + arena introspection + its workers' owner
        tables) plus registered DRIVER connections (drivers own most
        objects), then join store rows against the union of every
        process's reference set — an object resident in a store that NO
        process references is an unreachable-yet-undeleted leak, grouped
        by its creation callsite. The GCS object directory contributes
        locations. Merge runs on an executor thread (pure python over
        potentially 10k rows), mirroring steptrace_cluster's posture."""
        from ray_tpu._private import memview

        processes, n_nodes = await self._scrape_processes(
            "memview_node", "memview_snapshot",
            cfg.memview_scrape_timeout_s, tag_drivers=True)
        locations = {
            oid.hex(): sorted(nodes)
            for oid, nodes in list(self.object_dir.items())[:50_000]
        }
        merged = await asyncio.get_running_loop().run_in_executor(
            None, memview.merge_cluster, processes, locations)
        merged["nodes"] = n_nodes
        merged["errors"] = [proc for proc in processes
                            if proc.get("error")]
        return merged

    # ------------------------------------------------------------------
    # Task events (observability; ray: gcs_task_manager.h)
    # ------------------------------------------------------------------
    async def rpc_list_objects(self, conn: Connection, p):
        """Object directory view for the state API (centralized analog of
        ray: dashboard/state_aggregator.py list_objects)."""
        limit = (p or {}).get("limit") or 10_000
        out = []
        for oid, nodes in list(self.object_dir.items())[:limit]:
            out.append({"object_id": oid.hex(), "locations": sorted(nodes)})
        return out

    async def rpc_add_task_events(self, conn: Connection, p):
        self.task_events.extend(p["events"])
        overflow = len(self.task_events) - cfg.task_events_buffer_size
        if overflow > 0:
            del self.task_events[:overflow]
        return {}

    async def rpc_list_task_events(self, conn: Connection, p):
        return self.task_events[-(p.get("limit") or 1000):]
