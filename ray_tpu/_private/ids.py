"""Binary ID types with embedded lineage.

Mirrors the reference's ID hierarchy (ray: src/ray/common/id.h): JobID (4B) is
embedded in ActorID (16B), ActorID in TaskID (24B), and TaskID in ObjectID
(28B, TaskID + 4B little-endian return index). IDs are immutable bytes with
hex round-tripping; random IDs come from ``os.urandom``.
"""

from __future__ import annotations

import os

JOB_ID_SIZE = 4
ACTOR_ID_UNIQUE_BYTES = 12
ACTOR_ID_SIZE = ACTOR_ID_UNIQUE_BYTES + JOB_ID_SIZE  # 16
TASK_ID_UNIQUE_BYTES = 8
TASK_ID_SIZE = TASK_ID_UNIQUE_BYTES + ACTOR_ID_SIZE  # 24
OBJECT_ID_INDEX_BYTES = 4
OBJECT_ID_SIZE = TASK_ID_SIZE + OBJECT_ID_INDEX_BYTES  # 28
UNIQUE_ID_SIZE = 28
NODE_ID_SIZE = 28
WORKER_ID_SIZE = 28
PLACEMENT_GROUP_ID_SIZE = 18


class BaseID:
    """Immutable fixed-width binary identifier."""

    SIZE = UNIQUE_ID_SIZE
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, (bytes, bytearray)):
            raise TypeError(f"{type(self).__name__} requires bytes, got {type(binary)}")
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        object.__setattr__(self, "_binary", bytes(binary))
        object.__setattr__(self, "_hash", hash((type(self).__name__, bytes(binary))))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def is_nil(self) -> bool:
        return self._binary == b"\xff" * self.SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __ne__(self, other):
        return not self.__eq__(other)

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class UniqueID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class NodeID(BaseID):
    SIZE = NODE_ID_SIZE


class WorkerID(BaseID):
    SIZE = WORKER_ID_SIZE


class JobID(BaseID):
    SIZE = JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._binary, "little")


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(ACTOR_ID_UNIQUE_BYTES) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[ACTOR_ID_UNIQUE_BYTES:])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        nil_actor = b"\xff" * ACTOR_ID_UNIQUE_BYTES + job_id.binary()
        return cls(os.urandom(TASK_ID_UNIQUE_BYTES) + nil_actor)

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(os.urandom(TASK_ID_UNIQUE_BYTES) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        nil_actor = b"\xff" * ACTOR_ID_UNIQUE_BYTES + job_id.binary()
        return cls(b"\x00" * TASK_ID_UNIQUE_BYTES + nil_actor)

    def actor_id(self) -> ActorID:
        return ActorID(self._binary[TASK_ID_UNIQUE_BYTES:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE

    @classmethod
    def from_index(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Return object for the index-th return of task (1-based, like the ref)."""
        return cls(task_id.binary() + index.to_bytes(OBJECT_ID_INDEX_BYTES, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index to avoid colliding with returns.
        idx = put_index | 0x80000000
        return cls(task_id.binary() + idx.to_bytes(OBJECT_ID_INDEX_BYTES, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:TASK_ID_SIZE])

    def job_id(self) -> JobID:
        return self.task_id().job_id()

    def return_index(self) -> int:
        return int.from_bytes(self._binary[TASK_ID_SIZE:], "little") & 0x7FFFFFFF


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(os.urandom(PLACEMENT_GROUP_ID_SIZE - JOB_ID_SIZE) + job_id.binary())


# --- submit hot path: block minting + raw wire forms -------------------
# The per-call cost of ``TaskID.for_task(JobID(...))`` is an urandom
# syscall plus two ID-object constructions (each with an isinstance/size
# check and an eager hash); ``ObjectID.from_index`` adds an int.to_bytes
# per return. The submit fast path (worker.submit_from_template) works in
# raw bytes instead: TaskSpec.task_id is bytes on the wire anyway.

_IDX_BYTES = tuple(
    i.to_bytes(OBJECT_ID_INDEX_BYTES, "little") for i in range(256)
)
_CTR_BYTES = tuple(bytes((i,)) for i in range(256))


def object_id_binary(task_binary: bytes, index: int) -> bytes:
    """28-byte ObjectID wire form for the index-th return of a task (same
    layout as ``ObjectID.from_index``) without intermediate ID objects."""
    if index < 256:
        return task_binary + _IDX_BYTES[index]
    return task_binary + index.to_bytes(OBJECT_ID_INDEX_BYTES, "little")


class TaskIDMinter:
    """Amortized task-id minting: one ``os.urandom`` call covers a block
    of ``BLOCK`` ids — a 7-byte random prefix plus a block-local counter
    byte form the 8 unique bytes of a TaskID. One minter per (worker,
    remote function / actor); the 16-byte suffix (nil-actor + job for
    plain tasks, the actor id for actor tasks) is fixed at construction.

    Uniqueness matches per-call minting: two blocks collide with
    probability 2^-56, and ids within a block differ in the counter byte.

    Thread safety: the whole block is pre-built as a list and handed out
    via ``list.pop()`` (atomic under the GIL). Racing refills at block
    exhaustion each draw their own random prefix, so ids are never
    duplicated — at worst a partial block is abandoned."""

    BLOCK = 64
    __slots__ = ("_suffix", "_block")

    def __init__(self, suffix: bytes):
        if len(suffix) != ACTOR_ID_SIZE:
            raise ValueError(
                f"minter suffix must be {ACTOR_ID_SIZE} bytes, "
                f"got {len(suffix)}"
            )
        self._suffix = bytes(suffix)
        self._block: list = []

    @classmethod
    def for_job(cls, job_id: JobID) -> "TaskIDMinter":
        return cls(b"\xff" * ACTOR_ID_UNIQUE_BYTES + job_id.binary())

    @classmethod
    def for_actor(cls, actor_id: ActorID) -> "TaskIDMinter":
        return cls(actor_id.binary())

    def next_binary(self) -> bytes:
        """24-byte TaskID wire form; a fresh random block every BLOCK
        calls. Blocks hand out ids in descending counter order (pop from
        the tail is O(1)); order within a block carries no meaning."""
        try:
            return self._block.pop()
        except IndexError:
            prefix = os.urandom(TASK_ID_UNIQUE_BYTES - 1)
            suffix = self._suffix
            self._block = blk = [
                prefix + _CTR_BYTES[i] + suffix
                for i in range(self.BLOCK)
            ]
            return blk.pop()
