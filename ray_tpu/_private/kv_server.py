"""Standalone KV metadata server — the Redis-analog behind remote GCS
persistence.

Reference parity: ray src/ray/gcs/store_client/redis_store_client.h —
the reference can point GCS table storage at an external Redis so losing
the head node's disk doesn't lose cluster metadata. This is the same
contract as a ~100-line rpcio service: per-cluster namespaced tables,
ordered pipelined puts, full-snapshot load on GCS (re)start. Run it
anywhere the head can reach::

    python -m ray_tpu._private.kv_server --port 6479 [--path state.log]

and point the head at ``kv://host:6479`` (RAY_TPU_GCS_STORAGE or the
gcs_persist config). ``--path`` makes the KV server itself durable via
the same append-log the local GCS store uses; without it, durability is
"survives head loss, not KV-server loss" — exactly Redis-without-AOF.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
from typing import Dict

logger = logging.getLogger("ray_tpu.kv_server")


class KvService:
    """tables: cluster_id -> table -> key -> value (all values opaque)."""

    def __init__(self, persist_path: str = ""):
        self._clusters: Dict[str, Dict[str, dict]] = {}
        self._store = None
        if persist_path:
            from ray_tpu._private.gcs_store import FileLogStore

            self._store = FileLogStore(persist_path)
            snapshot = self._store.load()
            # persisted layout: table name = "<cluster_id>\x1f<table>"
            for combined, table in snapshot.items():
                cid, _, tname = combined.partition("\x1f")
                self._clusters.setdefault(cid, {})[tname] = dict(table)

    def rpc_kv_put(self, conn, p):
        cid = p.get("cluster_id", "")
        tables = self._clusters.setdefault(cid, {})
        for table, key, value in p["entries"]:
            t = tables.setdefault(table, {})
            if value is None:
                t.pop(key, None)
            else:
                t[key] = value
            if self._store is not None:
                self._store.put(f"{cid}\x1f{table}", key, value)
        return {}

    def rpc_kv_load(self, conn, p):
        cid = p.get("cluster_id", "")
        return {"tables": self._clusters.get(cid, {})}

    def rpc_kv_ping(self, conn, p):
        return {"ok": True}


async def amain(args):
    from ray_tpu._private.rpcio import RpcServer, enable_eager_tasks

    enable_eager_tasks(asyncio.get_running_loop())
    service = KvService(args.path)
    server = RpcServer(service, host=args.host, port=args.port)
    port = await server.start()
    # stdout protocol: the spawning parent reads this line for the port
    print(f"kv server listening on {args.host}:{port}", flush=True)  # lint: allow-print
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, args.port_file)
    await asyncio.Event().wait()  # serve forever


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--path", default="",
                        help="optional append-log for KV-server durability")
    parser.add_argument("--port-file", default="")
    args = parser.parse_args(argv)
    logging.basicConfig(level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"))
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
