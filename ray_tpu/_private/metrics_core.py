"""Process-local, dependency-free runtime metrics core.

Analog of the reference's native stats layer (ray: src/ray/stats/ — every
subsystem reports into OpenCensus views scraped per node). Here each
process owns one ``Registry`` of counters, gauges, and fixed-bucket
histograms; the RPC plane exposes a ``metrics_snapshot`` verb that dumps
it, raylets fan snapshots out to their workers, and the GCS fans out
cluster-wide and merges (sum counters/gauges, merge histogram buckets).

Design constraints, in order:

1. **record() must be cheap enough for the rpcio send path.** A latency
   histogram observation is: one module-global load (the enable flag),
   one int multiply, one ``int.bit_length()`` (the log2 bucket index —
   no search, no branch chain), one list increment, one float add.
   Measured ~0.3-0.6us on the bench box; the metrics-overhead lane in
   bench.py gates the self-measured instrumentation share at <2% of the
   sync-task hot path.
2. **No locks on the record path.** CPython's GIL makes the individual
   ``list[i] += 1`` / ``float +=`` updates effectively atomic enough for
   *statistics*: a torn read-modify-write across threads can lose an
   increment, never corrupt structure. Snapshots copy under the GIL the
   same way. (The reference accepts the same looseness in its per-thread
   OpenCensus measure buffers.)
3. **No dependencies.** Prometheus text rendering lives in
   ``ray_tpu.dashboard.prometheus`` over the same dump format the old KV
   pipeline used, so one exposition path serves both runtime and user
   metrics.

Bucketing: log2 ("exponential") buckets with a fixed floor, pre-sized at
construction. Two standard scales cover the runtime:

* ``LATENCY``: 1us floor, 26 buckets -> boundaries 1us..32s (+overflow).
* ``SIZE``: 1-byte floor, 31 buckets -> boundaries 1B..1GiB (+overflow).

The bucket index for value ``v`` is ``int(v / floor).bit_length()``
clamped to the overflow bucket; bucket ``i`` therefore holds values
``< floor * 2**i`` — cumulative counts line up with Prometheus ``le``
semantics (to within the open/closed edge, irrelevant at log2 width).
User-defined histograms (``ray_tpu.util.metrics``) may instead pass
explicit ``boundaries``; those take a bisect on record, which is fine
off the hot path.

Lifetime caveat: ``set_fn`` callbacks live in the process-global
registry and pin whatever they close over. That is by design for the
production topology (one raylet/GCS/replica per process — the component
IS the process); code that rebuilds a component in-process must
``registry().unregister()`` its metric names or re-register the same
labelsets (``set_fn`` on an existing child replaces the callback).
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "registry",
    "LATENCY", "SIZE", "set_enabled", "is_enabled", "record_calls",
    "merge_snapshots", "hist_quantiles", "summarize", "snapshot_records",
]

# Module-global enable flag: record paths read it once per call. Flipped
# by set_enabled() (the overhead A/B lane) or RAY_TPU_METRICS_ENABLED=0.
_enabled = os.environ.get("RAY_TPU_METRICS_ENABLED", "1").lower() not in (
    "0", "false", "no")
# Count of instrumentation events (inc/set/record calls) in this process:
# the self-measured overhead gate multiplies this by the measured
# per-event cost. The increment itself rides inside every timed event, so
# the measurement stays honest about its own bookkeeping.
_events = 0


def set_enabled(flag: bool):
    global _enabled
    _enabled = bool(flag)


def is_enabled() -> bool:
    return _enabled


def record_calls() -> int:
    """Total inc/set/record calls in this process since import."""
    return _events


# --- standard log2 scales ----------------------------------------------
LATENCY = ("log2", 1e-6, 26)   # 1us .. 32s
SIZE = ("log2", 1.0, 31)       # 1B .. 1GiB


def _log2_boundaries(lo: float, nb: int) -> List[float]:
    return [lo * (1 << i) for i in range(nb)]


class Counter:
    """Monotonic counter (one labelset). ``set_fn`` registers a callback
    evaluated at snapshot time instead — components that already keep
    their own monotonic tallies (raylet dispatch counters) expose them
    as proper Prometheus counters with zero hot-path cost."""

    __slots__ = ("tags", "_value", "_fn")

    def __init__(self, tags: Dict[str, str]):
        self.tags = tags
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def inc(self, n: float = 1.0):
        global _events
        if not _enabled:
            return
        _events += 1
        self._value += n

    def set_fn(self, fn: Callable[[], float]):
        self._fn = fn
        return self

    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return self._value
        return self._value

    def _series(self) -> dict:
        return {"tags": self.tags, "value": self.value()}


class Gauge:
    """Point-in-time value (one labelset). ``set_fn`` registers a
    callback evaluated at snapshot time instead — queue depths, pool
    sizes and breaker states cost ZERO on their hot paths this way."""

    __slots__ = ("tags", "_value", "_fn")

    def __init__(self, tags: Dict[str, str]):
        self.tags = tags
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float):
        global _events
        if not _enabled:
            return
        _events += 1
        self._value = v

    def inc(self, n: float = 1.0):
        global _events
        if not _enabled:
            return
        _events += 1
        self._value += n

    def dec(self, n: float = 1.0):
        self.inc(-n)

    def set_fn(self, fn: Callable[[], float]):
        self._fn = fn
        return self

    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return self._value
        return self._value

    def _series(self) -> dict:
        return {"tags": self.tags, "value": self.value()}


class Histogram:
    """Fixed-bucket distribution (one labelset).

    ``scale`` is ``LATENCY``/``SIZE`` (log2 index via bit_length) or
    ``boundaries`` is an explicit sorted list (bisect on record — the
    user-metrics path)."""

    __slots__ = ("tags", "_counts", "_sum", "_inv_lo", "_nb", "_bounds")

    def __init__(self, tags: Dict[str, str],
                 scale: Tuple = LATENCY,
                 boundaries: Optional[Sequence[float]] = None):
        self.tags = tags
        if boundaries is not None:
            self._bounds = sorted(float(b) for b in boundaries)
            self._inv_lo = None
            self._nb = len(self._bounds)
        else:
            _, lo, nb = scale
            self._bounds = _log2_boundaries(lo, nb)
            self._inv_lo = 1.0 / lo
            self._nb = nb
        self._counts = [0] * (self._nb + 1)
        self._sum = 0.0

    def record(self, v: float):
        global _events
        if not _enabled:
            return
        _events += 1
        inv = self._inv_lo
        if inv is not None:
            i = int(v * inv).bit_length()
            if i > self._nb:
                i = self._nb
        else:
            i = bisect_left(self._bounds, v)
        self._counts[i] += 1
        self._sum += v

    # alias matching the user-facing util.metrics API
    observe = record

    def count(self) -> int:
        return sum(self._counts)

    def _series(self) -> dict:
        return {
            "tags": self.tags,
            "buckets": list(self._counts),
            "boundaries": list(self._bounds),
            "sum": self._sum,
            "count": sum(self._counts),
        }


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One metric name; children per labelset. ``labels(**tags)`` is the
    (cached) child lookup — hot call sites resolve their child once and
    keep the reference. The family itself proxies inc/set/record to the
    unlabeled child for convenience."""

    def __init__(self, name: str, mtype: str, description: str = "",
                 **kwargs):
        self.name = name
        self.type = mtype
        self.description = description
        self._kwargs = kwargs
        self._children: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()

    @property
    def default(self):
        """The unlabeled child, created on first use — a labeled-only
        family must not emit a spurious empty series."""
        return self.labels()

    def labels(self, **tags):
        key = tuple(sorted(tags.items()))
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    cls = _TYPES[self.type]
                    child = cls(dict(tags), **self._kwargs) \
                        if self._kwargs else cls(dict(tags))
                    self._children[key] = child
        return child

    # convenience proxies (unlabeled child)
    def inc(self, n: float = 1.0):
        self.default.inc(n)

    def set(self, v: float):
        self.default.set(v)

    def dec(self, n: float = 1.0):
        self.default.dec(n)

    def set_fn(self, fn):
        return self.default.set_fn(fn)

    def record(self, v: float):
        self.default.record(v)

    observe = record

    def dump(self) -> dict:
        return {
            "name": self.name,
            "type": self.type,
            "description": self.description,
            "series": [c._series() for c in list(self._children.values())],
            "ts": time.time(),
        }


class Registry:
    """Per-process metric table; get-or-create by name."""

    def __init__(self):
        self._families: Dict[str, Family] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, mtype: str, description: str,
             **kwargs) -> Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = Family(name, mtype, description, **kwargs)
                    self._families[name] = fam
        if fam.type != mtype:
            raise ValueError(
                f"metric {name!r} already registered as {fam.type}")
        return fam

    def counter(self, name: str, description: str = "") -> Family:
        return self._get(name, "counter", description)

    def gauge(self, name: str, description: str = "") -> Family:
        return self._get(name, "gauge", description)

    def histogram(self, name: str, description: str = "",
                  scale: Tuple = LATENCY,
                  boundaries: Optional[Sequence[float]] = None) -> Family:
        return self._get(name, "histogram", description, scale=scale,
                         boundaries=boundaries)

    def unregister(self, name: str):
        with self._lock:
            self._families.pop(name, None)

    def snapshot(self) -> Dict[str, dict]:
        """{name: dump} for every registered metric. Series with zero
        activity are included (a just-registered histogram is a valid,
        empty distribution)."""
        return {name: fam.dump()
                for name, fam in list(self._families.items())}


_REGISTRY: Optional[Registry] = None
_REGISTRY_LOCK = threading.Lock()


def registry() -> Registry:
    """The process-wide default registry (what the runtime instruments
    and ``metrics_snapshot`` dumps)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                reg = Registry()
                try:
                    from ray_tpu._private.config import GLOBAL_CONFIG

                    set_enabled(GLOBAL_CONFIG.metrics_enabled)
                except Exception:
                    pass
                _REGISTRY = reg
    return _REGISTRY


# ---------------------------------------------------------------------------
# merge + summaries (the fan-out layers and scrape surfaces use these)
# ---------------------------------------------------------------------------
def merge_snapshots(snaps: Sequence[Dict[str, dict]]) -> Dict[str, dict]:
    """Fold per-process snapshots into one: counters and gauges SUM per
    labelset; histogram buckets merge elementwise when boundaries agree
    (a mismatched declaration is dropped rather than corrupting the
    merge — same posture as the Prometheus renderer)."""
    out: Dict[str, dict] = {}
    for snap in snaps:
        if not snap:
            continue
        for name, dump in snap.items():
            cur = out.get(name)
            if cur is None:
                out[name] = {
                    "name": name, "type": dump.get("type", "gauge"),
                    "description": dump.get("description", ""),
                    "series": [dict(s) for s in dump.get("series", ())],
                    "ts": dump.get("ts", 0.0),
                }
                continue
            cur["ts"] = max(cur["ts"], dump.get("ts", 0.0))
            by_tags = {tuple(sorted(s["tags"].items())): s
                       for s in cur["series"]}
            for s in dump.get("series", ()):
                key = tuple(sorted(s["tags"].items()))
                mine = by_tags.get(key)
                if mine is None:
                    cur["series"].append(dict(s))
                    continue
                if cur["type"] in ("counter", "gauge"):
                    mine["value"] = mine.get("value", 0.0) \
                        + float(s.get("value", 0.0))
                else:
                    if list(mine.get("boundaries", ())) != \
                            list(s.get("boundaries", ())):
                        continue  # mismatched declaration: drop this dump
                    mine["buckets"] = [
                        a + b for a, b in zip(mine["buckets"], s["buckets"])
                    ]
                    mine["sum"] = mine.get("sum", 0.0) + s.get("sum", 0.0)
                    mine["count"] = mine.get("count", 0) + s.get("count", 0)
    return out


def hist_quantiles(series: dict,
                   qs: Sequence[float] = (0.5, 0.95, 0.99)
                   ) -> Dict[float, float]:
    """Estimate quantiles from one histogram series' buckets (linear
    interpolation inside the landing bucket; the log2 widths keep the
    error within a factor of 2, which is what tail tracking needs)."""
    counts = series.get("buckets") or ()
    bounds = series.get("boundaries") or ()
    total = sum(counts)
    out = {q: 0.0 for q in qs}
    if total == 0:
        return out
    for q in qs:
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c:
                hi = bounds[i] if i < len(bounds) else bounds[-1] * 2.0
                lo = bounds[i - 1] if i >= 1 else 0.0
                frac = (rank - (cum - c)) / c
                out[q] = lo + (hi - lo) * frac
                break
    return out


def summarize(snapshot: Dict[str, dict]) -> Dict[str, dict]:
    """Compact per-metric summary: counters/gauges -> value per labelset;
    histograms -> count/sum/mean/p50/p95/p99 per labelset. This is what
    the CLI table, ``util.state.metrics_summary()``, and the dashboard
    history ring serve."""
    out: Dict[str, dict] = {}
    for name, dump in sorted(snapshot.items()):
        mtype = dump.get("type", "gauge")
        entry: Dict[str, Any] = {"type": mtype,
                                 "description": dump.get("description", "")}
        series_out = []
        for s in dump.get("series", ()):
            if mtype in ("counter", "gauge"):
                series_out.append({"tags": s.get("tags", {}),
                                   "value": s.get("value", 0.0)})
            else:
                count = s.get("count", 0)
                qs = hist_quantiles(s)
                series_out.append({
                    "tags": s.get("tags", {}),
                    "count": count,
                    "sum": s.get("sum", 0.0),
                    "mean": (s.get("sum", 0.0) / count) if count else 0.0,
                    "p50": qs[0.5], "p95": qs[0.95], "p99": qs[0.99],
                })
        entry["series"] = series_out
        out[name] = entry
    return out


def snapshot_records(snapshot: Dict[str, dict]) -> Dict[str, List[dict]]:
    """Adapt a (merged) snapshot to the ``{name: [dump, ...]}`` records
    shape the Prometheus renderer consumes."""
    return {name: [dump] for name, dump in snapshot.items()}


def process_snapshot(role: str, extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """The ``metrics_snapshot`` RPC payload: this process's registry dump
    plus identity for slicing and the event count for the overhead gate."""
    out: Dict[str, Any] = {
        "role": role,
        "pid": os.getpid(),
        "record_calls": _events,
        "metrics": registry().snapshot(),
    }
    if extra:
        out.update(extra)
    return out
