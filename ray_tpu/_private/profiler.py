"""On-demand sampling profilers: CPU flamegraphs + memory diffs.

Dependency-free analog of the reference's dashboard profiling
(ray: dashboard/modules/reporter/profile_manager.py, which shells out to
py-spy/memray against a live pid). Here every process carries its own
profiler and exposes it over the existing RPC plane instead:

- ``CpuSampler``: a background thread walking ``sys._current_frames()``
  at a configurable rate, accumulating collapsed stacks. It self-measures
  its own per-sample cost and auto-throttles when sampling would exceed a
  target overhead fraction, so attaching to a loaded worker stays safe.
- ``MemProfiler``: tracemalloc start/snapshot/diff — top-N allocation
  sites with size/count deltas against the start-of-window baseline.
- ``ProfilerService``: one per process (gcs/raylet/worker/driver), the
  object RPC handlers delegate to (start/stop/status/run verbs).

Per-task attribution: executors tag their user-code threads via
``tag_current_thread`` with the currently-executing task/actor id; the
sampler prepends synthetic ``actor:<id>``/``task:<name>`` frames to that
thread's stacks, so a merged cluster flamegraph slices per task/actor.

Export: collapsed-stack text (flamegraph.pl / speedscope paste) and
speedscope JSON (one sampled profile per process, shared frame table).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# per-thread task attribution (written by executors, read by the sampler)
# ---------------------------------------------------------------------------
# thread ident -> ("actor"|"task", id_hex, name). Plain dict ops are atomic
# under the GIL; the sampler tolerates torn reads (a sample attributed one
# task late is noise, not corruption).
_THREAD_TAGS: Dict[int, Tuple[str, str, str]] = {}


class tag_current_thread:
    """Context manager: attribute samples of the calling thread to a task
    or actor while user code runs. ~2 dict ops of overhead per task."""

    __slots__ = ("_tag", "_ident", "_prev")

    def __init__(self, name: str, task_id: Optional[str] = None,
                 actor_id: Optional[str] = None):
        if actor_id:
            self._tag = ("actor", actor_id, name)
        else:
            self._tag = ("task", task_id or "", name)

    @classmethod
    def for_spec(cls, spec) -> "tag_current_thread":
        if spec.actor_id is not None:
            return cls(spec.method_name or spec.name,
                       actor_id=spec.actor_id.hex())
        return cls(spec.name, task_id=spec.task_id.hex()[:16])

    def __enter__(self):
        self._ident = threading.get_ident()
        self._prev = _THREAD_TAGS.get(self._ident)
        _THREAD_TAGS[self._ident] = self._tag
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            _THREAD_TAGS.pop(self._ident, None)
        else:
            _THREAD_TAGS[self._ident] = self._prev


def current_thread_tag() -> Optional[Tuple[str, str, str]]:
    return _THREAD_TAGS.get(threading.get_ident())


# ---------------------------------------------------------------------------
# CPU sampling profiler
# ---------------------------------------------------------------------------
_MAX_STACK_DEPTH = 64
_MAX_UNIQUE_STACKS = 20_000
_OVERFLOW_KEY = "<stack-table-overflow>"


def _frame_label(frame) -> str:
    code = frame.f_code
    fname = code.co_filename
    # last two path components keep labels readable AND distinct across
    # same-named files (worker.py exists in several packages)
    parts = fname.replace("\\", "/").rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) > 1 else fname
    return f"{code.co_name} ({short}:{frame.f_lineno})"


class CpuSampler:
    """Sampling wall-clock profiler for THIS process, all threads.

    ``stacks`` maps a ``;``-joined root-first frame list to its sample
    count (the collapsed-stack convention). Synthetic root frames:
    ``thread:<name>`` always, then ``actor:<id>``/``task:<name>`` when the
    sampled thread is tagged by an executor.
    """

    def __init__(self, hz: float = 100.0,
                 max_overhead_fraction: float = 0.05,
                 max_duration_s: float = 600.0):
        self.hz = max(0.1, float(hz))
        self.max_overhead = max(1e-9, float(max_overhead_fraction))
        self.max_duration_s = max_duration_s
        self.interval = 1.0 / self.hz
        # keyed by TUPLE of frame labels while sampling (hashing a tuple
        # of interned strings is far cheaper than building a joined
        # string per sample); collect() renders the ';' form
        self.stacks: Dict[tuple, int] = {}
        self.samples = 0
        self.throttled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = 0.0
        self._ended_at = 0.0
        self._sample_cost_s = 0.0  # cumulative time spent inside _sample
        self._lock = threading.Lock()
        # (id(code) -> (code, {lineno: label})): formatting a frame label
        # costs ~1us; hot stacks repeat, so cache by code identity (the
        # code object is PINNED in the value, so the id cannot be reused)
        self._label_cache: Dict[int, tuple] = {}
        self._thread_names: Dict[int, str] = {}
        self._names_refreshed = 0.0

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    def start(self):
        if self.running:
            raise RuntimeError("cpu sampler already running")
        self._stop.clear()
        self._started_at = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="cpu-sampler", daemon=True
        )
        self._thread.start()

    def _run(self):
        own = threading.get_ident()
        deadline = self._started_at + self.max_duration_s
        # rolling per-sample cost for the throttle decision (EWMA so one
        # slow GC-paused sample doesn't throttle the whole session)
        avg_cost = 0.0
        while not self._stop.is_set():
            t0 = time.monotonic()
            if t0 > deadline:
                break  # leak-proof: a lost stop() can't sample forever
            try:
                self._sample(own)
            except Exception:
                pass  # a torn frame walk must never kill the sampler
            cost = time.monotonic() - t0
            self._sample_cost_s += cost
            avg_cost = cost if avg_cost == 0.0 else \
                0.8 * avg_cost + 0.2 * cost
            # self-throttle: keep (time sampling / wall time) under the
            # overhead budget by growing the interval when samples are
            # expensive (many threads, deep stacks)
            if avg_cost > self.max_overhead * self.interval:
                self.interval = min(avg_cost / self.max_overhead, 1.0)
                self.throttled = True
            self._stop.wait(max(self.interval - cost, 0.001))
        self._ended_at = time.monotonic()

    def _cached_label(self, frame) -> str:
        code = frame.f_code
        lineno = frame.f_lineno
        entry = self._label_cache.get(id(code))
        if entry is None or entry[0] is not code:
            if len(self._label_cache) > 8192:
                self._label_cache.clear()
            entry = self._label_cache[id(code)] = (code, {})
        label = entry[1].get(lineno)
        if label is None:
            label = entry[1][lineno] = _frame_label(frame)
        return label

    def _thread_name(self, ident: int, now: float) -> str:
        # threading.enumerate() per sample is a measurable cost; names
        # change ~never, so refresh the cache lazily
        name = self._thread_names.get(ident)
        if name is None or now - self._names_refreshed > 2.0:
            self._thread_names = {
                t.ident: f"thread:{t.name}" for t in threading.enumerate()
            }
            self._names_refreshed = now
            name = self._thread_names.get(ident, f"thread:{ident}")
        return name

    def _sample(self, own_ident: int):
        now = time.monotonic()
        frames = sys._current_frames()
        cached = self._cached_label
        with self._lock:
            self.samples += 1
            for ident, frame in frames.items():
                if ident == own_ident:
                    continue
                stack: List[str] = [self._thread_name(ident, now)]
                tag = _THREAD_TAGS.get(ident)
                if tag is not None:
                    kind, id_hex, name = tag
                    stack.append(f"{kind}:{id_hex}")
                    stack.append(f"{'method' if kind == 'actor' else 'fn'}"
                                 f":{name}")
                prefix_len = len(stack)
                depth = 0
                f = frame
                while f is not None and depth < _MAX_STACK_DEPTH:
                    stack.append(cached(f))
                    f = f.f_back
                    depth += 1
                # root first past the synthetic prefix (collapsed form)
                stack[prefix_len:] = stack[:prefix_len - 1:-1]
                key = tuple(stack)
                n = self.stacks.get(key)
                if n is None and len(self.stacks) >= _MAX_UNIQUE_STACKS:
                    key = (_OVERFLOW_KEY,)
                    n = self.stacks.get(key)
                self.stacks[key] = (n or 0) + 1

    def collect(self, reset: bool = False) -> Dict[str, Any]:
        """Snapshot without stopping (collapsed string form)."""
        with self._lock:
            stacks = {";".join(k): n for k, n in self.stacks.items()}
            samples = self.samples
            if reset:
                self.stacks = {}
                self.samples = 0
        end = self._ended_at or time.monotonic()
        elapsed = max(end - self._started_at, 1e-9)
        return {
            "kind": "cpu",
            "pid": os.getpid(),
            "duration_s": round(elapsed, 4),
            "samples": samples,
            "effective_hz": round(samples / elapsed, 2),
            "requested_hz": self.hz,
            "overhead_fraction": round(self._sample_cost_s / elapsed, 6),
            "throttled": self.throttled,
            "stacks": stacks,
        }

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        return self.collect()


# ---------------------------------------------------------------------------
# memory profiler (tracemalloc)
# ---------------------------------------------------------------------------
class MemProfiler:
    """tracemalloc session: start -> (snapshot|diff) -> stop.

    ``collect(diff=True)`` reports the top-N allocation sites by net
    growth since ``start()`` — the "what leaked during this window" view;
    ``diff=False`` reports absolute top sites."""

    def __init__(self, n_frames: int = 8):
        self.n_frames = max(1, int(n_frames))
        self._baseline = None
        self._we_started = False
        self._started_at = 0.0

    @property
    def running(self) -> bool:
        return self._baseline is not None

    def start(self):
        import tracemalloc

        if self.running:
            raise RuntimeError("memory profiler already running")
        if not tracemalloc.is_tracing():
            tracemalloc.start(self.n_frames)
            self._we_started = True
        self._started_at = time.monotonic()
        self._baseline = tracemalloc.take_snapshot()

    @staticmethod
    def _site(tb) -> str:
        # leaf-last "file:lineno <- caller:lineno" chain, shortened paths
        frames = []
        for fr in list(tb)[:4]:
            fname = fr.filename.replace("\\", "/").rsplit("/", 2)
            frames.append(f"{'/'.join(fname[-2:])}:{fr.lineno}")
        return " <- ".join(frames)

    def collect(self, top_n: int = 30, diff: bool = True) -> Dict[str, Any]:
        import tracemalloc

        if not self.running:
            raise RuntimeError("memory profiler not running")
        snap = tracemalloc.take_snapshot()
        filters = [tracemalloc.Filter(False, tracemalloc.__file__),
                   tracemalloc.Filter(False, "<frozen importlib._bootstrap>")]
        snap = snap.filter_traces(filters)
        sites = []
        if diff:
            base = self._baseline.filter_traces(filters)
            stats = snap.compare_to(base, "traceback")
            stats.sort(key=lambda s: abs(s.size_diff), reverse=True)
            for s in stats[:top_n]:
                sites.append({
                    "site": self._site(s.traceback),
                    "size_bytes": s.size, "count": s.count,
                    "size_diff_bytes": s.size_diff,
                    "count_diff": s.count_diff,
                })
        else:
            for s in snap.statistics("traceback")[:top_n]:
                sites.append({
                    "site": self._site(s.traceback),
                    "size_bytes": s.size, "count": s.count,
                })
        current, peak = tracemalloc.get_traced_memory()
        return {
            "kind": "mem",
            "pid": os.getpid(),
            "duration_s": round(time.monotonic() - self._started_at, 4),
            "diff": diff,
            "traced_current_bytes": current,
            "traced_peak_bytes": peak,
            "sites": sites,
        }

    def stop(self, top_n: int = 30, diff: bool = True) -> Dict[str, Any]:
        import tracemalloc

        out = self.collect(top_n=top_n, diff=diff)
        self._baseline = None
        if self._we_started and tracemalloc.is_tracing():
            tracemalloc.stop()
        self._we_started = False
        return out


# ---------------------------------------------------------------------------
# per-process service (RPC handlers delegate here)
# ---------------------------------------------------------------------------
class ProfilerService:
    """One per process; owns at most one live profiler of each kind."""

    def __init__(self, role: str):
        self.role = role
        self._cpu: Optional[CpuSampler] = None
        self._mem: Optional[MemProfiler] = None
        self._lock = threading.Lock()

    def _cfg(self):
        from ray_tpu._private.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG

    def start(self, p: Dict[str, Any]) -> Dict[str, Any]:
        cfg = self._cfg()
        kind = p.get("kind", "cpu")
        with self._lock:
            if kind == "cpu":
                if self._cpu is not None and self._cpu.running:
                    return {"error": "cpu profiler already running"}
                hz = min(float(p.get("hz") or cfg.profiler_default_hz),
                         cfg.profiler_max_hz)
                self._cpu = CpuSampler(
                    hz=hz,
                    max_overhead_fraction=float(
                        p.get("max_overhead")
                        or cfg.profiler_max_overhead_fraction),
                    max_duration_s=cfg.profiler_max_duration_s,
                )
                self._cpu.start()
            elif kind == "mem":
                if self._mem is not None and self._mem.running:
                    return {"error": "memory profiler already running"}
                self._mem = MemProfiler(
                    n_frames=int(p.get("n_frames")
                                 or cfg.profiler_mem_frames))
                self._mem.start()
            else:
                return {"error": f"unknown profiler kind {kind!r}"}
        return {"ok": True, "kind": kind}

    def stop(self, p: Dict[str, Any]) -> Dict[str, Any]:
        cfg = self._cfg()
        kind = p.get("kind", "cpu")
        with self._lock:
            if kind == "cpu":
                if self._cpu is None:
                    return {"error": "cpu profiler not running"}
                prof, self._cpu = self._cpu, None
                out = prof.stop()
            elif kind == "mem":
                if self._mem is None:
                    return {"error": "memory profiler not running"}
                prof, self._mem = self._mem, None
                out = prof.stop(
                    top_n=int(p.get("top_n") or cfg.profiler_mem_top_n),
                    diff=bool(p.get("diff", True)),
                )
            else:
                return {"error": f"unknown profiler kind {kind!r}"}
        out["role"] = self.role
        return out

    def status(self) -> Dict[str, Any]:
        return {
            "role": self.role,
            "pid": os.getpid(),
            "cpu_running": self._cpu is not None and self._cpu.running,
            "mem_running": self._mem is not None and self._mem.running,
        }

    async def run(self, p: Dict[str, Any]) -> Dict[str, Any]:
        """start -> sleep(duration) -> stop, as one awaited operation (the
        shape the fan-out layers use: no cross-request session state to
        lose when a connection drops mid-window)."""
        import asyncio

        cfg = self._cfg()
        duration = min(float(p.get("duration") or 5.0),
                       cfg.profiler_max_duration_s)
        started = self.start(p)
        if started.get("error"):
            return started
        try:
            await asyncio.sleep(duration)
        finally:
            out = self.stop(p)
        return out


# ---------------------------------------------------------------------------
# merge + export
# ---------------------------------------------------------------------------
def merge_profiles(processes: List[Dict[str, Any]],
                   kind: str = "cpu") -> Dict[str, Any]:
    """Fold per-process results into one cluster view: summed collapsed
    stacks for cpu, summed per-site deltas for mem. Per-process results
    ride along (they carry node/pid/actor identity for slicing)."""
    procs = [p for p in processes if p and not p.get("error")]
    errors = [p for p in processes if p and p.get("error")]
    out: Dict[str, Any] = {"kind": kind, "processes": procs,
                           "errors": errors}
    if kind == "cpu":
        merged: Dict[str, int] = {}
        total = 0
        for p in procs:
            total += p.get("samples", 0)
            for stack, n in (p.get("stacks") or {}).items():
                merged[stack] = merged.get(stack, 0) + n
        out["stacks"] = merged
        out["samples"] = total
    else:
        by_site: Dict[str, Dict[str, Any]] = {}
        for p in procs:
            for s in p.get("sites") or ():
                e = by_site.setdefault(s["site"], {
                    "site": s["site"], "size_bytes": 0, "count": 0,
                    "size_diff_bytes": 0, "count_diff": 0,
                })
                e["size_bytes"] += s.get("size_bytes", 0)
                e["count"] += s.get("count", 0)
                e["size_diff_bytes"] += s.get("size_diff_bytes", 0)
                e["count_diff"] += s.get("count_diff", 0)
        out["sites"] = sorted(by_site.values(),
                              key=lambda e: abs(e["size_diff_bytes"])
                              or e["size_bytes"], reverse=True)
    return out


def to_collapsed(stacks: Dict[str, int]) -> str:
    """flamegraph.pl / speedscope-paste format: one 'stack count' line."""
    lines = [f"{stack} {count}"
             for stack, count in sorted(stacks.items(),
                                        key=lambda kv: -kv[1])]
    return "\n".join(lines) + ("\n" if lines else "")


def to_speedscope(processes: List[Dict[str, Any]],
                  name: str = "ray_tpu cpu profile") -> Dict[str, Any]:
    """Speedscope file (https://www.speedscope.app/file-format-schema.json):
    one 'sampled' profile per process over a shared frame table, so a
    cluster-wide capture opens as switchable per-process flamegraphs."""
    frame_index: Dict[str, int] = {}
    frames: List[Dict[str, str]] = []

    def fidx(label: str) -> int:
        i = frame_index.get(label)
        if i is None:
            i = frame_index[label] = len(frames)
            frames.append({"name": label})
        return i

    profiles = []
    for p in processes:
        stacks = p.get("stacks") or {}
        samples, weights = [], []
        for stack, count in stacks.items():
            samples.append([fidx(lbl) for lbl in stack.split(";")])
            weights.append(count)
        label = ":".join(str(x) for x in (
            p.get("role", "proc"), p.get("node_id", "")[:8] or None,
            p.get("pid")) if x)
        profiles.append({
            "type": "sampled",
            "name": label,
            "unit": "none",
            "startValue": 0,
            "endValue": sum(weights) or 1,
            "samples": samples,
            "weights": weights,
        })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": profiles or [{
            "type": "sampled", "name": "empty", "unit": "none",
            "startValue": 0, "endValue": 1, "samples": [], "weights": [],
        }],
        "name": name,
        "activeProfileIndex": 0,
        "exporter": "ray_tpu",
    }
