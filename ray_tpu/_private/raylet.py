"""Raylet: per-node agent — scheduling, worker pool, object management.

Analog of the reference's raylet (ray: src/ray/raylet/node_manager.h:119):

- ClusterTaskManager (ray: scheduling/cluster_task_manager.h:33-42): pick a
  feasible node from the GCS-synced cluster view (hybrid pack/spread policy),
  spill to a peer raylet or queue locally.
- LocalTaskManager (ray: local_task_manager.h:58): dependency-gated dispatch —
  pull plasma args local, acquire resources, bind an idle worker, push task.
- WorkerPool (ray: worker_pool.h:156): spawn/cache Python worker processes
  keyed by job; dedicated workers for actors.
- Object manager (ray: src/ray/object_manager/object_manager.h:117): chunked
  peer-to-peer object transfer into the node-local shm store, pull admission.
- Placement-group bundle resources via 2-phase prepare/commit
  (ray: placement_group_resource_manager.h).

TPU delta vs the reference: node resources advertise "TPU" chips plus ICI
topology labels so STRICT_PACK bundles map onto one slice; there is no
CUDA_VISIBLE_DEVICES analog — one worker process owns all local chips.
"""

from __future__ import annotations

import asyncio
import heapq
import logging
import os
import pickle
import subprocess
import sys
import tempfile
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private import object_store
from ray_tpu._private.common import (
    NodeInfo,
    TaskSpec,
    pick_node,
    res_add,
    res_fits,
    res_sub,
)
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private import faultsim, logplane
from ray_tpu._private.rpcio import (Connection, Finalized, RpcError,
                                    RpcServer, call_with_retries, connect,
                                    spawn)

logger = logging.getLogger(__name__)


def runtime_env_hash(runtime_env: Optional[dict]) -> str:
    """Stable hash of a runtime env; workers are pooled per hash
    (ray: worker_pool.h keyed by runtime-env hash)."""
    if not runtime_env:
        return ""
    import json

    return json.dumps(runtime_env, sort_keys=True)


class _Worker:
    def __init__(self, proc: subprocess.Popen, job_id: Optional[bytes],
                 env_hash: str = "", log_path: Optional[str] = None,
                 cidfile: Optional[str] = None, engine: Optional[str] = None,
                 spawn_id: Optional[str] = None):
        self.proc = proc
        self.job_id = job_id
        self.env_hash = env_hash
        # spawn key the worker echoes back in register_client: under a
        # real container engine the in-container worker's os.getpid()
        # differs from proc.pid (the engine CLIENT's pid), so pid-keyed
        # matching can never resolve — the spawn id is the identity
        self.spawn_id = spawn_id
        # container bookkeeping: SIGKILL on the engine client never
        # reaches the container — kill paths must also `engine rm -f`
        self.cidfile = cidfile
        self.engine = engine
        self.conn: Optional[Connection] = None
        self.client_id: Optional[str] = None
        self.busy_with: Optional[bytes] = None  # task_id
        self.actor_id: Optional[bytes] = None
        # direct task push (ray: direct_task_transport.cc worker leases):
        # the worker's own RPC port drivers push to, and the lease id
        # while a driver holds this worker
        self.direct_port: Optional[int] = None
        self.lease_id: Optional[str] = None
        self.registered = asyncio.get_running_loop().create_future()
        self.started_at = time.monotonic()
        self.oom_killed = False
        # log streaming (ray: _private/log_monitor.py): the raylet tails
        # this file and publishes new lines to drivers
        self.log_path = log_path
        self.log_offset = 0
        self.log_partial = b""
        # byte-range -> task-name attribution for streamed lines, fed
        # from the task events flowing through this raylet (logplane.py)
        self.log_spans = logplane.SpanTable(cfg.log_span_history)
        # fallback prefix name for lines outside any task span (set to
        # the actor class once this worker becomes an actor)
        self.log_name: Optional[str] = None
        # unattributed lines held for ONE tail tick so a racing RUNNING
        # event can land and win attribution over the fallback prefix
        self.log_held: list = []  # [(absolute_offset, raw_line), ...]

    def kill_process(self):
        """Kill the worker AND its container, if any: a plain kill only
        reaches the container-engine client process (SIGKILL is never
        proxied inside), which would leak a live container holding its
        ports and store mappings."""
        try:
            self.proc.kill()
        except OSError:
            pass
        if self.cidfile and self.engine:
            try:
                with open(self.cidfile) as f:
                    cid = f.read().strip()
                if cid:
                    subprocess.Popen(
                        [self.engine, "rm", "-f", cid],
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
            except OSError:
                pass


def _tail_worker_log(w: _Worker, final: bool = False):
    """Read newly appended bytes of one worker's log and attribute each
    line to its task by byte offset (w.log_spans). Returns
    ``(entry, stats)`` — entry is a batch record ``{pid, job_id, segs}``
    with ``segs`` = consecutive-line groups ``[task_name_or_None,
    [lines...]]``, or None when nothing new. ``final`` drains to EOF and
    flushes the partial line (worker exiting — its last write IS the
    traceback). Chunk bytes are split into fresh ``bytes`` objects before
    anything retains them: relay paths must never hold exported
    memoryviews of reused buffers (see the documented GC tp_clear
    hazard)."""
    stats = {"lines": 0, "bytes": 0, "truncated": 0}
    if not w.log_path:
        return None, stats
    lines_out = []  # (absolute_start_offset, raw_line)
    pos = w.log_offset - len(w.log_partial)
    budget = cfg.log_publish_max_bytes  # per-tick cap: keeps a chatty
    # worker from monopolizing the tick without letting it lag unboundedly
    try:
        with open(w.log_path, "rb") as f:
            f.seek(w.log_offset)
            while True:
                chunk = f.read(65536)
                if not chunk:
                    break
                w.log_offset += len(chunk)
                data = w.log_partial + chunk
                *lines, w.log_partial = data.split(b"\n")
                for ln in lines:
                    lines_out.append((pos, ln))
                    pos += len(ln) + 1
                budget -= len(chunk)
                if not final and budget <= 0:
                    break  # bounded per tick; the next tick continues
    except OSError:
        return None, stats
    if final and w.log_partial:
        lines_out.append((pos, w.log_partial))
        w.log_partial = b""
    # One-tick hold for unattributed lines (closes the PR 7 cosmetic
    # race, widened in PR 16): a line printed before its task's
    # RUNNING/FINISHED event reached this raylet used to publish with
    # the fallback prefix immediately. Worker-side task events are now
    # debounced (task_events_flush_interval_s, 20ms default), so the
    # window where log bytes exist but their span does not is real for
    # EVERY worker, not just actors — fresh lines that resolve to no
    # span are carried to the next tick (the tail interval, 0.3s,
    # comfortably exceeds the debounce window, so the span has landed
    # by the second look). Order-preserving (everything after the first
    # held line holds with it); carried lines always publish on their
    # second look (resolved, or the fallback for genuinely task-less
    # output), so the delay is bounded at one log_tail_interval_s.
    held = getattr(w, "log_held", None) or []
    w.log_held = []
    n_held = len(held)
    all_lines = held + lines_out
    segs: list = []  # [[task_name_or_None, [text...]], ...]
    for i, (off, raw) in enumerate(all_lines):
        if not raw:
            continue
        name = w.log_spans.resolve(off)
        if name is None and not final and i >= n_held:
            w.log_held = [ln for ln in all_lines[i:] if ln[1]]
            break
        name = name or w.log_name
        raw, truncated = logplane.truncate_line(raw, cfg.log_max_line_bytes)
        stats["truncated"] += truncated
        stats["lines"] += 1
        stats["bytes"] += len(raw)
        text = raw.decode("utf-8", "replace")
        if segs and segs[-1][0] == name:
            segs[-1][1].append(text)
        else:
            segs.append([name, [text]])
    # never prune spans still ahead of a held line's second look
    w.log_spans.prune(w.log_held[0][0] if w.log_held
                      else w.log_offset - len(w.log_partial))
    if not segs:
        return None, stats
    return {
        "pid": w.proc.pid,
        "job_id": w.job_id.hex() if w.job_id else None,
        "segs": segs,
    }, stats


def _feed_log_span(w: _Worker, ev: dict):
    """Fold one task event's log fields into the worker's span table
    (direct-push workers self-report events through rpc_task_events;
    raylet-routed tasks stamp events in _run_on_worker)."""
    if ev.get("log_end") is not None and ev.get("log_start") is not None:
        w.log_spans.close_span(ev["task_id"], ev.get("name"),
                               ev["log_start"], ev["log_end"])
    elif ev.get("log_start") is not None:
        w.log_spans.open_span(ev["task_id"], ev.get("name"), ev["log_start"])


# Pull priorities (ray: pull_manager.h:31-38 BundlePriority — Get before
# Wait before TaskArgs).
PULL_PRIO_GET = 0
PULL_PRIO_WAIT = 1
PULL_PRIO_TASK_ARGS = 2


class _PullGate:
    """Pull admission control (ray: pull_manager.h:56 PullManager).

    Limits concurrent inbound transfers by slot count and by an in-flight
    byte budget, granting waiters in (priority, FIFO) order. A pull learns
    its size from the first chunk and then ``charge``s the budget; the sole
    active pull may always overshoot so a single huge object still
    transfers (the reference's "admit at least one bundle" rule)."""

    def __init__(self, max_concurrent: int, byte_budget: int):
        self.max_concurrent = max_concurrent
        self.byte_budget = byte_budget
        self._active = 0
        self._bytes = 0
        self._seq = 0
        self._waiters: List[tuple] = []  # heap of (priority, seq, future)

    async def acquire(self, priority: int):
        if self._active < self.max_concurrent and not self._waiters:
            self._active += 1
            return
        fut = asyncio.get_running_loop().create_future()
        self._seq += 1
        heapq.heappush(self._waiters, (priority, self._seq, fut))
        await fut

    async def charge(self, nbytes: int):
        """Reserve transfer bytes; waits while the budget is exhausted by
        OTHER active transfers (never blocks the only charged pull)."""
        while self._bytes > 0 and self._bytes + nbytes > self.byte_budget:
            await asyncio.sleep(0.02)
        self._bytes += nbytes

    def uncharge(self, nbytes: int):
        self._bytes -= nbytes

    def release_slot(self):
        self._active -= 1
        while self._waiters and self._active < self.max_concurrent:
            _, _, fut = heapq.heappop(self._waiters)
            if not fut.done():
                self._active += 1
                fut.set_result(None)


class _ReadyQueues:
    """Dispatchable tasks, FIFO per scheduling class (ray:
    cluster_task_manager.cc keys queues by SchedulingClass). The dispatch
    loop skips a whole blocked class in O(1) instead of churning every
    queued task through a flat deque each wakeup."""

    __slots__ = ("by_cls", "_n")

    def __init__(self):
        self.by_cls: Dict[tuple, deque] = {}
        self._n = 0

    def append(self, qt: "_QueuedTask"):
        self.by_cls.setdefault(qt.sched_cls, deque()).append(qt)
        self._n += 1

    def push_front(self, qt: "_QueuedTask"):
        self.by_cls.setdefault(qt.sched_cls, deque()).appendleft(qt)
        self._n += 1

    def pop_head(self, cls: tuple) -> "_QueuedTask":
        q = self.by_cls[cls]
        qt = q.popleft()
        if not q:
            del self.by_cls[cls]
        self._n -= 1
        return qt

    def remove_task(self, task_id: bytes) -> Optional["_QueuedTask"]:
        for cls, q in self.by_cls.items():
            for i, qt in enumerate(q):
                if qt.spec.task_id == task_id:
                    del q[i]
                    if not q:
                        del self.by_cls[cls]
                    self._n -= 1
                    return qt
        return None

    def __len__(self):
        return self._n

    def __iter__(self):
        for q in self.by_cls.values():
            yield from q


class _QueuedTask:
    __slots__ = ("spec", "resources", "pending_deps", "worker", "sched_cls",
                 "ready_at")

    def __init__(self, spec: TaskSpec, resources: Dict[str, float]):
        self.spec = spec
        self.resources = resources
        self.pending_deps: Set[bytes] = set()
        self.worker: Optional[_Worker] = None
        # computed once: the dispatch loop touches it every pass, and
        # recomputing (a sort) per pass profiled at ~90 calls per task
        self.sched_cls = spec.scheduling_class()
        # stamped when the task enters the ready queue (placement-latency
        # histogram measures ready -> dispatched-to-worker); requeues
        # (push_front) keep the original stamp on purpose
        self.ready_at = 0.0


class Raylet:
    def __init__(
        self,
        gcs_host: str,
        gcs_port: int,
        session_dir: str,
        resources: Dict[str, float],
        labels: Dict[str, str] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        node_id: Optional[str] = None,
    ):
        self.node_id = node_id or NodeID.from_random().hex()
        # chaos identity: partition rules target "<node_id>><peer_addr>"
        faultsim.set_self_id(self.node_id)
        self.gcs_host, self.gcs_port = gcs_host, gcs_port
        self.session_dir = session_dir
        self.host = host
        self.server = RpcServer(self, host, port)
        self.store_dir = os.path.join(session_dir, f"store_{self.node_id[:12]}")
        # Spill target lives on real disk, NOT /dev/shm: spilling must
        # actually relieve memory (ray: object_spilling_config external
        # storage). A non-file URI (s3://, custom scheme) passes through
        # UN-scoped: spill keys are object ids, so a restarted raylet can
        # restore its predecessor's externally-spilled objects.
        from ray_tpu._private.external_storage import is_local_spill_uri

        if cfg.external_storage_setup_module:
            # plugin hook: the module registers custom spill schemes via
            # register_external_storage_scheme before the store is built
            import importlib

            importlib.import_module(cfg.external_storage_setup_module)
        if cfg.object_spill_dir and not is_local_spill_uri(
                cfg.object_spill_dir):
            self.spill_dir = cfg.object_spill_dir
        else:
            spill_root = cfg.object_spill_dir or os.path.join(
                tempfile.gettempdir(), "ray_tpu_spill"
            )
            self.spill_dir = os.path.join(
                spill_root, f"spill_{self.node_id[:12]}"
            )
        self.store = object_store.make_local_store(
            self.store_dir, cfg.object_store_memory, self.spill_dir
        )
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.labels = labels or {}
        # Advertise this node's torus coordinate to the gang scheduler
        # (topology.py reads these labels off the GCS node table). Config-
        # synthesized for now, like the reference's TPU slice env vars;
        # explicit labels win over the config flags.
        if cfg.torus_coord:
            self.labels.setdefault("torus-coord", cfg.torus_coord)
        if cfg.torus_dims:
            self.labels.setdefault("torus-dims", cfg.torus_dims)
        self.gcs: Optional[Connection] = None
        self.cluster_view: Dict[str, NodeInfo] = {}
        self.peers: Dict[str, Connection] = {}
        # Client registry: client_id -> Connection (drivers + workers on node)
        self.clients: Dict[str, Connection] = {}
        # Worker pool (idle queues keyed by runtime-env hash)
        self.idle_workers: Dict[str, deque] = {}
        self.all_workers: Dict[int, _Worker] = {}  # pid -> worker
        # spawn_id -> worker: the registration key that survives pid
        # translation through container engines (see _Worker.spawn_id)
        self._workers_by_spawn: Dict[str, _Worker] = {}
        self.workers_by_client: Dict[str, _Worker] = {}
        self.local_actors: Dict[bytes, _Worker] = {}
        self.actor_addr_cache: Dict[bytes, tuple] = {}
        # Task queues
        self.waiting: Dict[bytes, _QueuedTask] = {}  # waiting on deps
        self.ready = _ReadyQueues()
        self.running: Dict[bytes, _QueuedTask] = {}
        # Tasks no cluster node can currently fit (ray: infeasible queue);
        # reported as autoscaler demand, retried as capacity appears.
        self.infeasible: Dict[bytes, _QueuedTask] = {}
        self.dep_waiters: Dict[bytes, List[bytes]] = {}  # object -> task_ids
        self.dep_owners: Dict[bytes, tuple] = {}  # object -> owner addr
        self.pg_bundles: Dict[Tuple[str, int], Dict[str, float]] = {}
        # per-actor FIFO routing (ordered delivery; see rpc_submit_task)
        self._actor_route_queues: Dict[bytes, deque] = {}
        self._actor_routers: set = set()
        # tick-batched task_result delivery: owner -> payload list (one
        # notify frame per owner per tick instead of one per task)
        self._owner_outbox: Dict[tuple, list] = {}
        self._owner_flushing = False
        # worker leases for direct task push (ray: lease_policy.h +
        # direct_task_transport.cc): lease_id -> {worker, resources,
        # client_id}. Leased workers hold their resources and are out of
        # the idle pool until returned/reclaimed.
        self._leases: Dict[str, dict] = {}
        # recently-dead workers (client_id -> reason), so lease holders
        # can resolve why a direct connection dropped
        self._worker_fates: Dict[str, str] = {}
        self._pulls_inflight: Dict[bytes, asyncio.Future] = {}
        # push plane (ray: push_manager.h): (oid, node) dedup + per-peer
        # chunk pipelines + receiver-side assembly buffers
        self._pushes_inflight: Dict[tuple, asyncio.Future] = {}
        self._push_peer_sems: Dict[str, asyncio.Semaphore] = {}
        # in-flight actor creations: a retried create_actor (caller
        # deadline raced a slow worker spawn) joins the pending future
        # instead of spawning a second worker for the same actor_id
        self._actors_creating: Dict[bytes, asyncio.Future] = {}
        # in-flight worker spawns per env hash + wakeup for waiters
        # (requests wait on a booting same-env worker instead of racing
        # another spawn against it)
        self._workers_starting: Dict[str, int] = {}
        self._spawn_waiters: Dict[str, int] = {}
        self._worker_started = asyncio.Event()
        self._push_rx: Dict[bytes, dict] = {}
        self._pull_gate = _PullGate(
            cfg.max_concurrent_pulls,
            int(cfg.object_store_memory * cfg.pull_manager_memory_fraction),
        )
        self._rr = [0]
        # tasks we spilled elsewhere and must resubmit if that node dies:
        # target_node_id -> {task_id: spec}
        self._spilled_away: Dict[str, Dict[bytes, TaskSpec]] = {}
        # spill_done notices that raced ahead of our own bookkeeping (a
        # chained re-spill can settle before our spill_submit await
        # resumes); matched and removed in _schedule_or_queue
        self._spill_released: set = set()
        # strong refs to fire-and-forget loop tasks (the event loop holds
        # tasks weakly; a GC'd pending task would silently drop its work)
        self._bg_tasks: set = set()
        self._tasks: List[asyncio.Task] = []
        self._dispatch_event = asyncio.Event()
        self._stopping = False
        self.port = None
        # metrics
        self.counters = {"tasks_dispatched": 0, "tasks_spilled": 0,
                         "objects_pulled": 0, "log_lines_published": 0,
                         "log_bytes_published": 0, "log_lines_truncated": 0}
        # log plane: "logs"-channel subscriber count piggybacked on the
        # heartbeat reply (-1 = unknown yet -> tail); tailer CPU seconds
        # accumulate for the BENCH_LOG_OVERHEAD self-measured share
        self._log_subscribers = -1
        self._log_tail_cpu_s = 0.0
        self._setup_metrics()
        # Task state-transition buffer, flushed in batches to the GCS
        # (ray: src/ray/core_worker/task_event_buffer.h:199 — we buffer at
        # the raylet, the chokepoint that sees queue/dispatch/finish for
        # every normal task on this node).
        self._task_events: List[dict] = []

    def _setup_metrics(self):
        """Register this raylet's runtime gauges (metrics_core.py).
        Every gauge is a snapshot-time callback — scheduler/pool hot
        paths pay nothing — tagged with the node id so the cluster merge
        keeps one series per node (ray parity: src/ray/stats/metric_defs.h
        scheduler/worker-pool gauges)."""
        from ray_tpu._private import metrics_core as mc

        reg = mc.registry()
        tags = {"node": self.node_id[:12]}

        def gauge(name, desc, fn):
            reg.gauge(name, desc).labels(**tags).set_fn(fn)

        gauge("raylet_ready_queue_depth",
              "Tasks ready for dispatch on this node",
              lambda: len(self.ready))
        gauge("raylet_waiting_tasks",
              "Tasks parked waiting on argument fetches",
              lambda: len(self.waiting))
        gauge("raylet_infeasible_tasks",
              "Tasks no cluster node can currently fit",
              lambda: len(self.infeasible))
        gauge("raylet_running_tasks", "Tasks executing on this node",
              lambda: len(self.running))
        gauge("raylet_worker_pool_size", "Live worker processes",
              lambda: len(self.all_workers))
        gauge("raylet_idle_workers", "Idle pooled workers",
              lambda: sum(len(q) for q in self.idle_workers.values()))
        gauge("raylet_store_used_bytes", "Local object store usage",
              self.store.used_bytes)
        # *_total series must expose TYPE counter (rate() and openmetrics
        # lint assume it); the raylet already keeps the tallies, so these
        # are snapshot-time counter callbacks
        reg.counter("raylet_tasks_dispatched_total",
                    "Tasks handed to workers").labels(**tags).set_fn(
            lambda: self.counters["tasks_dispatched"])
        reg.counter("raylet_tasks_spilled_total",
                    "Tasks spilled to peer nodes").labels(**tags).set_fn(
            lambda: self.counters["tasks_spilled"])
        gauge("raylet_store_spilled_objects",
              "Objects currently spilled out of shm",
              lambda: self.store.spilled_stats()["spilled_objects"])
        # memory observatory (memview.py): arena occupancy gauges on the
        # cluster scrape — dead bytes inside live segments are the
        # hole-punch reclamation candidates, and a pooled segment pinned
        # by a reader's SHARED flock is a stuck-view leak. Guarded: the
        # native store (slab_arena=0) has no arena ledger.
        st = self.store
        if hasattr(st, "arena_dead_bytes"):
            gauge("slab_arena_dead_bytes",
                  "Dead (hole-punch-reclaimable) bytes inside live slab "
                  "segments", st.arena_dead_bytes)
            gauge("slab_arena_live_bytes",
                  "Live object bytes resident in slab segments",
                  st.arena_live_bytes)
            gauge("slab_arena_fragmentation_ratio",
                  "dead / (live + dead) resident slab bytes",
                  st.arena_fragmentation)
        if hasattr(st, "arena_punched_bytes"):
            # cumulative punch-pass yield: *_total counter semantics so
            # rate() shows reclamation activity on the cluster scrape
            reg.counter(
                "slab_arena_punched_dead_bytes_total",
                "Dead bytes retired from live segments by the "
                "hole-punch reclamation pass",
            ).labels(**tags).set_fn(st.arena_punched_bytes)
        if hasattr(st, "pool_pinned"):
            # TTL-cached: a flock probe per pooled file per scrape is
            # cheap, but metrics scrapes can arrive from several pollers
            reg.gauge(
                "slab_segments_pinned",
                "Recycling-pool segments kept alive only by a reader's "
                "SHARED flock",
            ).labels(**dict(tags, reason="reader_flock")).set_fn(
                lambda: len(st.pool_pinned(max_age_s=5.0)))
        # log plane self-measurement (channel-tagged: the "logs" pubsub
        # channel is the only one carrying log records today)
        ltags = dict(tags, channel="logs")
        reg.counter("raylet_log_lines_published_total",
                    "Worker log lines published to the logs channel"
                    ).labels(**ltags).set_fn(
            lambda: self.counters["log_lines_published"])
        reg.counter("raylet_log_bytes_published_total",
                    "Worker log bytes published to the logs channel"
                    ).labels(**ltags).set_fn(
            lambda: self.counters["log_bytes_published"])
        reg.counter("raylet_log_lines_truncated_total",
                    "Log lines cut at log_max_line_bytes before publish"
                    ).labels(**ltags).set_fn(
            lambda: self.counters["log_lines_truncated"])
        reg.counter("raylet_log_tail_cpu_seconds_total",
                    "CPU seconds spent tailing+attributing worker logs"
                    ).labels(**ltags).set_fn(lambda: self._log_tail_cpu_s)
        # path="raylet": ready-queue entry -> worker dispatch on this
        # node. The driver-side direct-lease pump records the same family
        # with path="direct" (enqueue -> push to a leased worker), so the
        # live histogram schedsim calibrates against covers BOTH dispatch
        # paths (plain driver tasks bypass the raylet ready queue).
        self._placement_lat = reg.histogram(
            "raylet_task_placement_latency_seconds",
            "Task ready to dispatched-to-worker, by dispatch path",
            scale=mc.LATENCY,
        ).labels(**dict(tags, path="raylet"))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    async def start(self):
        self.port = await self.server.start()
        self.gcs = await connect(self.gcs_host, self.gcs_port, handler=self, name="gcs-conn")
        reply = await self.gcs.request(
            "register_node", self._register_payload(), timeout=cfg.gcs_rpc_timeout_s
        )
        self._on_view(reply["nodes"])
        self._tasks.append(spawn(self._heartbeat_loop()))
        self._tasks.append(spawn(self._dispatch_loop()))
        self._tasks.append(
            spawn(self._memory_monitor_loop())
        )
        self._tasks.append(
            spawn(self._task_event_flush_loop())
        )
        self._tasks.append(
            spawn(self._infeasible_retry_loop())
        )
        self._tasks.append(
            spawn(self._log_tailer_loop())
        )
        if hasattr(self.store, "punch_holes"):
            self._tasks.append(spawn(self._punch_loop()))
        if cfg.enable_node_agent:
            spawn(self._start_agent())
        if cfg.worker_prestart > 0:
            spawn(self._prestart_workers())
        logger.info("raylet %s listening on %s", self.node_id[:8], self.port)
        return self.port

    async def _prestart_workers(self):
        """Warm the idle pool at boot (ray: worker_pool.cc PrestartWorkers
        / prestart_worker_first_driver): a worker process costs several
        seconds of interpreter+import time, and paying it during startup
        overlaps with driver setup instead of the first task's latency."""
        n = min(int(cfg.worker_prestart),
                max(1, int(self.resources_total.get("CPU", 1))))
        for _ in range(n):
            if len(self.all_workers) >= cfg.num_workers_soft_limit:
                return
            try:
                w = await self._start_worker(None, None)
                if w is not None and w.lease_id is None \
                        and w.busy_with is None:
                    self._return_worker(w)
            except Exception:
                logger.debug("worker prestart failed", exc_info=True)
                return

    async def _start_agent(self):
        """Spawn this node's dashboard agent (ray: agent_manager.h — a
        per-node agent process serving node-local HTTP: stats, logs,
        stacks). Its port registers in the GCS KV so the head/operators
        can find it; failure is non-fatal (agents are observability)."""

        from ray_tpu._private.node import control_plane_env

        port_file = os.path.join(
            self.session_dir, f"agent_port_{self.node_id[:8]}"
        )
        try:
            self.agent_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.dashboard.agent",
                 "--raylet-port", str(self.port),
                 "--session-dir", self.session_dir,
                 "--port-file", port_file],
                # control-plane process: must not re-gain the TPU-plugin
                # trigger (and its jax import) from the stash
                env=control_plane_env(),
                stdout=open(os.path.join(
                    self.session_dir, "logs", f"agent_{self.node_id[:8]}.out"
                ), "ab"),
                stderr=subprocess.STDOUT,
            )
            for _ in range(100):  # aiohttp import can take a moment
                if os.path.exists(port_file):
                    break
                await asyncio.sleep(0.1)
            with open(port_file) as f:
                self.agent_port = int(f.read().strip())
            await call_with_retries(
                lambda: self.gcs, "kv_put", {
                    "ns": b"node_agents", "key": self.node_id.encode(),
                    "value": str(self.agent_port).encode(),
                })
        except Exception:
            logger.warning("node agent failed to start", exc_info=True)

    # ------------------------------------------------------------------
    # worker-log streaming (ray: _private/log_monitor.py — the per-node
    # monitor tails worker log files and publishes attributed lines on
    # the GCS "logs" pubsub channel so subscribed drivers can print them)
    # ------------------------------------------------------------------
    async def _publish_worker_logs(self, batch):
        if not batch:
            return
        try:
            # rides the GCS's batched pubsub outbox (gcs._publish): a
            # burst of per-worker entries costs one frame per subscriber
            await self.gcs.request("publish", {
                "channel": "logs",
                "message": {"node_id": self.node_id, "workers": batch},
            })
        except Exception:
            pass

    def _log_resume_bounded(self):
        """A subscriber appeared after a zero-subscriber window in which
        tailing was skipped entirely. Resume from where the tailer
        stopped — NOT from EOF: the subscriber count is heartbeat-lagged
        (up to heartbeat_interval_s stale), so a task that printed right
        after the driver subscribed would have its lines silently
        skipped by an EOF jump. Instead cap the backlog at one tick
        budget; the driver's job filter drops foreign-job history
        anyway."""
        for w in self.all_workers.values():
            if not w.log_path:
                continue
            try:
                size = os.path.getsize(w.log_path)
            except OSError:
                continue
            floor = max(0, size - cfg.log_publish_max_bytes)
            if w.log_offset < floor:
                w.log_offset = floor
                w.log_partial = b""

    async def _log_tailer_loop(self):
        while True:
            await asyncio.sleep(cfg.log_tail_interval_s)
            if self._log_subscribers == 0:
                # nobody is listening (heartbeat-reported subscriber
                # count): skip even the file reads — an unwatched
                # cluster pays nothing for the log plane
                continue
            # thread_time, not perf_counter: the counter advertises CPU
            # seconds, and on a busy raylet wall time inside this loop is
            # mostly GIL/scheduler waits — it would overstate the share
            # the BENCH_LOG_OVERHEAD lane gates by several x
            t0 = time.thread_time()
            batch = []
            for w in list(self.all_workers.values()):
                entry, stats = _tail_worker_log(w)
                self._log_account(stats)
                if entry:
                    batch.append(entry)
            self._log_tail_cpu_s += time.thread_time() - t0
            await self._publish_worker_logs(batch)

    def _log_account(self, stats):
        if stats is None:
            return
        self.counters["log_lines_published"] += stats["lines"]
        self.counters["log_bytes_published"] += stats["bytes"]
        self.counters["log_lines_truncated"] += stats["truncated"]

    # ------------------------------------------------------------------
    # task events (observability; ray: task_event_buffer.h:199)
    # ------------------------------------------------------------------
    def _emit_task_event(self, spec: TaskSpec, state: str, **extra):
        ev = {
            "task_id": spec.task_id.hex(),
            "name": spec.name,
            "job_id": spec.job_id.hex() if spec.job_id else None,
            "actor_id": spec.actor_id.hex() if spec.actor_id else None,
            "attempt": spec.attempt,
            "state": state,
            "ts": time.time(),
            "node_id": self.node_id,
        }
        ev.update(extra)
        self._task_events.append(ev)

    async def _task_event_flush_loop(self):
        while True:
            await asyncio.sleep(cfg.metrics_report_interval_s)
            if not self._task_events:
                continue
            batch, self._task_events = self._task_events, []
            try:
                await self.gcs.request("add_task_events", {"events": batch})
            except Exception:
                # GCS unreachable: requeue a bounded amount.
                batch.extend(self._task_events)
                self._task_events = batch[-cfg.task_events_buffer_size:]

    # ------------------------------------------------------------------
    # OOM defense (ray: common/memory_monitor.h:52 MemoryMonitor +
    # raylet/worker_killing_policy.h)
    # ------------------------------------------------------------------
    def _memory_usage_fraction(self) -> float:
        if cfg.memory_monitor_test_path:
            try:
                with open(cfg.memory_monitor_test_path) as f:
                    return float(f.read().strip())
            except (OSError, ValueError):
                return 0.0
        try:
            with open("/proc/meminfo") as f:
                info = {}
                for line in f:
                    parts = line.split()
                    info[parts[0].rstrip(":")] = int(parts[1])
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", total)
            if total <= 0:
                return 0.0
            return 1.0 - avail / total
        except OSError:
            return 0.0

    def _pick_oom_victim(self) -> Optional[_Worker]:
        """Worker-killing policy: prefer workers running retriable normal
        tasks, newest-first (their lost progress is smallest and the task
        resubmits); then non-actor busy workers; never idle pool workers
        (killing them frees little) and actors only as a last resort —
        matching the spirit of ray: worker_killing_policy_group_by_owner.h."""
        busy = [w for w in self.all_workers.values()
                if w.busy_with is not None or w.lease_id is not None]
        if not busy:
            return None

        def retriable(w: _Worker) -> bool:
            if w.lease_id is not None:
                # leased to a driver for direct push: the owner retries on
                # conn loss, so treat like a retriable normal task
                return True
            qt = self.running.get(w.busy_with)
            return qt is not None and qt.spec.max_retries != 0

        tiers = (
            [w for w in busy if w.actor_id is None and retriable(w)],
            [w for w in busy if w.actor_id is None],
            busy,
        )
        for tier in tiers:
            if tier:
                return max(tier, key=lambda w: w.started_at)
        return None

    async def _memory_monitor_loop(self):
        while True:
            await asyncio.sleep(cfg.memory_monitor_refresh_ms / 1000.0)
            usage = self._memory_usage_fraction()
            if usage <= cfg.memory_usage_threshold:
                continue
            victim = self._pick_oom_victim()
            if victim is None:
                continue
            logger.warning(
                "memory usage %.2f over threshold %.2f: killing worker "
                "pid=%s (task=%s)", usage, cfg.memory_usage_threshold,
                victim.proc.pid,
                victim.busy_with.hex()[:16] if victim.busy_with else None,
            )
            victim.oom_killed = True
            self.counters["workers_oom_killed"] = (
                self.counters.get("workers_oom_killed", 0) + 1
            )
            try:
                await self.gcs.request("add_event", {
                    "severity": "WARNING", "source": "raylet",
                    "label": "WORKER_OOM_KILLED",
                    "message": (
                        f"memory usage {usage:.2f} over threshold "
                        f"{cfg.memory_usage_threshold:.2f}: killed worker "
                        f"pid={victim.proc.pid}"
                    ),
                    "fields": {"node_id": self.node_id,
                               "pid": victim.proc.pid},
                })
            except Exception:
                pass
            try:
                victim.kill_process()
            except Exception:
                pass

    def _register_payload(self) -> dict:
        """Node registration incl. a report of what this raylet is actually
        running, so a restarted GCS reconciles its replayed tables
        (reference analog: node_manager.proto:358 NotifyGCSRestart +
        RayletNotifyGCSRestart, core_worker.proto:417)."""
        return {
            "node_id": self.node_id,
            "host": self.host,
            "port": self.port,
            "store_dir": self.store_dir,
            "resources_total": self.resources_total,
            "labels": self.labels,
            "state": {
                "actors_running": {
                    aid: w.client_id for aid, w in self.local_actors.items()
                    if w.client_id
                },
                "objects": list(self.store.object_ids()),
                "pg_bundles": [[pg_id, idx] for (pg_id, idx) in self.pg_bundles],
            },
        }

    async def _gcs_reconnect_loop(self):
        """The GCS connection dropped (GCS died or restarted): keep retrying
        until it accepts us again, then re-register with our live state
        (ray: gcs_rpc_server_reconnect_timeout_s — but we retry until the
        raylet itself is stopped; the GCS owns deciding we are dead)."""
        delay = 0.2
        while not self._stopping:
            try:
                # few retries per cycle: the OUTER loop owns long-horizon
                # pacing, and a short inner dial keeps post-recovery
                # reconnect latency low (connect()'s full 30-attempt
                # backoff could leave us sleeping seconds after the GCS
                # is already back)
                conn = await connect(self.gcs_host, self.gcs_port, handler=self,
                                     name="gcs-conn", retries=3)
                reply = await conn.request(
                    "register_node", self._register_payload(),
                    timeout=cfg.gcs_rpc_timeout_s,
                )
                self.gcs = conn
                self._on_view(reply["nodes"])
                logger.info("raylet %s reconnected to GCS", self.node_id[:8])
                return
            except Exception:
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 2.0)

    async def stop(self):
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        for w in list(self.all_workers.values()):
            try:
                w.proc.terminate()
            except Exception:
                pass
        agent = getattr(self, "agent_proc", None)
        if agent is not None:
            try:
                agent.kill()
            except Exception:
                pass
        await self.server.stop()
        if self.gcs:
            await self.gcs.close()

    def _pending_demand(self) -> List[Dict[str, float]]:
        """Resource demand of queued tasks (infeasible + ready +
        dep-waiting), aggregated by shape with counts so a unique shape is
        never truncated away (ray: ResourceLoad aggregates by scheduling
        class before capping)."""
        shapes: Dict[tuple, dict] = {}
        for qt in (list(self.infeasible.values()) + list(self.ready)
                   + list(self.waiting.values())):
            res = qt.spec.resources
            if not res:
                continue
            key = tuple(sorted(res.items()))
            entry = shapes.get(key)
            if entry is None:
                shapes[key] = {"bundle": dict(res), "count": 1}
            else:
                entry["count"] += 1
        return list(shapes.values())[:100]  # cap on DISTINCT shapes

    def _is_idle(self) -> bool:
        """Safe-to-terminate idle: nothing queued or running, no actors,
        all resources returned, and no objects in the local store (a
        primary copy here may be the only copy in the cluster)."""
        return (
            not self.running and not self.ready and not self.waiting
            and not self.infeasible and not self.local_actors
            and self.resources_available == self.resources_total
            and not self.store.object_ids()
        )

    async def _heartbeat_loop(self):
        while True:
            try:
                reply = await self.gcs.request(
                    "heartbeat",
                    {
                        "node_id": self.node_id,
                        "resources_available": dict(self.resources_available),
                        # totals change at runtime (PG prepare adds named
                        # bundle resources); without this, other raylets
                        # judge _pg_* demand infeasible cluster-wide and
                        # bundle-scheduled work parks forever
                        "resources_total": dict(self.resources_total),
                        "pending_demand": self._pending_demand(),
                        "idle": self._is_idle(),
                    },
                    timeout=cfg.gcs_rpc_timeout_s,
                )
                if reply.get("reregister"):
                    # GCS restarted without dropping our conn (or evicted
                    # us): re-register with our live state.
                    reply = await self.gcs.request(
                        "register_node", self._register_payload(),
                        timeout=cfg.gcs_rpc_timeout_s,
                    )
                    self._on_view(reply["nodes"])
                subs = reply.get("log_subscribers")
                if subs is not None:
                    if self._log_subscribers == 0 and subs > 0:
                        self._log_resume_bounded()
                    self._log_subscribers = subs
            except (RpcError, OSError):
                # transient (RpcError covers ConnectionLost/RpcTimeoutError):
                # the reconnect loop (on_disconnect) owns recovery; the next
                # tick re-reports our state. Counted so chaos tests can see
                # the unhealthy window.
                self.counters["gcs_rpc_failures"] = (
                    self.counters.get("gcs_rpc_failures", 0) + 1
                )
            except Exception:
                logger.exception("heartbeat failed (non-transport)")
            # reclaim byte charges of push sessions whose sender died
            # (waiting for the next inbound push to sweep could wedge the
            # shared transfer budget indefinitely)
            try:
                self._expire_push_rx(time.monotonic())
            except Exception:
                pass
            await asyncio.sleep(cfg.heartbeat_interval_s)

    async def _punch_loop(self):
        """Periodic hole-punch reclamation: walk the arena's dead entry
        ranges (the memory observatory's ``dead_ranges`` — PR 12 shipped
        the measurement basis, this pass consumes it) and
        fallocate(PUNCH_HOLE|KEEP_SIZE) page-aligned interiors of
        fragmented sealed segments, returning tmpfs pages without
        waiting for whole-segment emptiness. Runs on an executor thread:
        the pass holds the store lock over flock probes + file ops."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(cfg.slab_punch_interval_s)
            if not cfg.slab_punch_enabled:
                continue
            try:
                out = await loop.run_in_executor(None,
                                                 self.store.punch_holes)
                if out.get("dead_bytes_retired"):
                    logger.info(
                        "hole-punch pass reclaimed %d dead bytes "
                        "(%d ranges in %d segment(s), %d punched; "
                        "%d pinned segment(s) skipped)",
                        out["dead_bytes_retired"], out["punched_ranges"],
                        out["segments"], out["punched_bytes"],
                        out["skipped_pinned"],
                    )
            except Exception:
                logger.exception("hole-punch pass failed")

    # ------------------------------------------------------------------
    # cluster view sync
    # ------------------------------------------------------------------
    def rpc_cluster_view(self, conn, view):
        self._on_view(view)

    def _on_view(self, view):
        died = []
        for n in view:
            prev = self.cluster_view.get(n["node_id"])
            info = NodeInfo(
                node_id=n["node_id"], host=n["host"], port=n["port"],
                store_dir=n["store_dir"], resources_total=n["resources_total"],
                labels=n.get("labels", {}),
            )
            info.resources_available = n["resources_available"]
            info.alive = n["alive"]
            self.cluster_view[n["node_id"]] = info
            if prev is not None and prev.alive and not info.alive:
                died.append(n["node_id"])
        # Keep our own availability authoritative locally.
        me = self.cluster_view.get(self.node_id)
        if me:
            me.resources_available = self.resources_available
            me.resources_total = self.resources_total
        for node_id in died:
            self._resubmit_spilled_to(node_id)
            self._push_peer_sems.pop(node_id, None)
        self._dispatch_event.set()

    def _resubmit_spilled_to(self, node_id: str):
        """A node we spilled tasks to died before reporting them settled:
        schedule them again from here (at-least-once for tasks caught
        mid-flight by a node failure — the reference re-executes such tasks
        through the owner's lease-failure retry path)."""
        stranded = self._spilled_away.pop(node_id, None)
        if not stranded:
            return
        logger.warning(
            "node %s died with %d task(s) we spilled there; resubmitting",
            node_id[:12], len(stranded),
        )
        loop = asyncio.get_running_loop()
        for spec in stranded.values():
            spec.origin_node = None
            t = spawn(self._schedule_or_queue(spec))
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)

    async def _peer(self, node_id: str) -> Optional[Connection]:
        conn = self.peers.get(node_id)
        if conn and not conn.closed:
            return conn
        info = self.cluster_view.get(node_id)
        if info is None or not info.alive:
            return None
        try:
            conn = await connect(info.host, info.port, handler=self,
                                 name=f"peer:{node_id[:8]}", retries=5)
        except Exception:
            # visible chaos window: partition tests assert on this count
            self.counters["peer_dial_failures"] = (
                self.counters.get("peer_dial_failures", 0) + 1
            )
            return None
        await conn.request("register_peer", {"node_id": self.node_id})
        # stamp the dial side too: faultsim partition rules and disconnect
        # bookkeeping can then identify the peer by node id, matching what
        # register_peer records on the accepting side
        conn.meta.update(kind="peer", node_id=node_id)
        self.peers[node_id] = conn
        return conn

    async def rpc_register_peer(self, conn: Connection, p):
        conn.meta.update(kind="peer", node_id=p["node_id"])
        return {}

    # ------------------------------------------------------------------
    # client (core worker) registry
    # ------------------------------------------------------------------
    async def rpc_register_client(self, conn: Connection, p):
        conn.meta.update(kind=p["kind"], client_id=p["client_id"], pid=p.get("pid"),
                         job_id=p.get("job_id"))
        self.clients[p["client_id"]] = conn
        if p["kind"] == "worker":
            # Spawn-id first: a containerized worker reports its
            # IN-CONTAINER pid, which differs from the engine-client pid
            # all_workers is keyed by (conmon/containerd-shim reparenting
            # — even --pid=host doesn't preserve it). Pid matching stays
            # as the fallback for pre-fix workers mid rolling upgrade.
            w = None
            if p.get("spawn_id"):
                w = self._workers_by_spawn.get(p["spawn_id"])
            if w is None:
                w = self.all_workers.get(p.get("pid"))
            if w is not None:
                w.conn = conn
                w.client_id = p["client_id"]
                w.direct_port = p.get("direct_port")
                self.workers_by_client[p["client_id"]] = w
                if not w.registered.done():
                    w.registered.set_result(w)
        return {"node_id": self.node_id, "store_dir": self.store_dir,
                "resources_total": self.resources_total, "labels": self.labels,
                # clients with a lease-capable store use the slab-arena
                # put path; others fall back to one-file writes
                "arena": bool(getattr(self.store, "arena_enabled", False))}

    def on_disconnect(self, conn: Connection):
        if conn is self.gcs:
            if not self._stopping:
                logger.warning("raylet %s lost GCS connection; reconnecting",
                               self.node_id[:8])
                return self._gcs_reconnect_loop()
            return None
        kind = conn.meta.get("kind")
        if kind in ("driver", "worker"):
            cid = conn.meta.get("client_id")
            self.clients.pop(cid, None)
            self._reclaim_client_slabs(cid)
            if kind == "driver":
                self._reclaim_client_leases(cid)
            if kind == "worker":
                return self._on_worker_conn_lost(cid)
        elif kind == "peer":
            peer_id = conn.meta.get("node_id")
            self.peers.pop(peer_id, None)
            self.counters["peer_conns_lost"] = (
                self.counters.get("peer_conns_lost", 0) + 1
            )
            # drop the per-peer push pipeline with the peer (elastic
            # clusters churn nodes; semaphores must not accumulate)
            self._push_peer_sems.pop(peer_id, None)

    async def _on_worker_conn_lost(self, client_id: str):
        w = self.workers_by_client.pop(client_id, None)
        if w is None:
            return
        self.all_workers.pop(w.proc.pid, None)
        if w.spawn_id:
            self._workers_by_spawn.pop(w.spawn_id, None)
        # record the fate so lease holders can ask WHY their direct conn
        # dropped (e.g. surface the OOM kill instead of a generic loss)
        if w.oom_killed:
            fate = (f"worker killed by the memory monitor under memory "
                    f"pressure (pid={w.proc.pid}); the task will be "
                    f"retried if retriable")
        else:
            fate = f"worker died while executing (pid={w.proc.pid})"
        self._worker_fates[client_id] = fate
        while len(self._worker_fates) > 256:
            self._worker_fates.pop(next(iter(self._worker_fates)))
        # final log drain: the crash traceback lands in the file right as
        # the process exits, after the tailer's last tick — deliver it.
        # Skipped entirely at zero subscribers: the tailer has been
        # skipping too, so this read would synchronously chew through the
        # whole untailed backlog on the event loop just to discard it
        # (and count never-published lines in the published counters).
        entry = None
        if self._log_subscribers != 0:
            entry, stats = _tail_worker_log(w, final=True)
            self._log_account(stats)
        if entry:
            t = spawn(
                self._publish_worker_logs([entry])
            )
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)
        pool = self.idle_workers.get(w.env_hash)
        if pool is not None:
            try:
                pool.remove(w)
            except ValueError:
                pass
        if w.lease_id is not None:
            # leased worker died: free its reservation; the lease holder
            # sees its direct connection drop and retries via the raylet
            self._release_lease(w.lease_id, worker_alive=False)
        if w.actor_id is not None:
            self.local_actors.pop(w.actor_id, None)
            try:
                await self.gcs.request(
                    "actor_died",
                    {"actor_id": w.actor_id, "intended": getattr(w, "kill_intended", False),
                     "reason": f"actor worker exited (pid={w.proc.pid})"},
                )
            except Exception:
                pass
        if w.busy_with is not None:
            qt = self.running.pop(w.busy_with, None)
            if qt is not None:
                res_add(self.resources_available, qt.resources)
                if w.oom_killed:
                    reason = (
                        f"worker killed by the memory monitor under memory "
                        f"pressure (pid={w.proc.pid}); the task will be "
                        f"retried if retriable"
                    )
                else:
                    reason = f"worker died while executing (pid={w.proc.pid})"
                await self._send_task_failure(qt.spec, reason, retriable=True,
                                              worker_died=True)
        self._dispatch_event.set()

    # ------------------------------------------------------------------
    # task submission path (ClusterTaskManager)
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # worker leases (direct task push)
    # ------------------------------------------------------------------
    async def rpc_lease_workers(self, conn: Connection, p):
        """Grant up to ``count`` local workers to the calling driver for
        direct task push (ray: raylet grants worker leases and the core
        worker pushes tasks straight to the leased worker,
        src/ray/core_worker/transport/direct_task_transport.cc). Each
        lease reserves ``resources`` exactly like a running task."""
        import uuid

        resources = dict(p["resources"])
        count = max(1, int(p.get("count", 1)))
        job_id = p.get("job_id") or conn.meta.get("job_id")
        client_id = conn.meta.get("client_id")
        granted = []
        for _ in range(count):
            if not res_fits(resources, self.resources_available):
                break
            w = await self._pop_worker_for(job_id, p.get("runtime_env"))
            if w is None:
                break
            # the await above can change availability; re-check before
            # reserving, and never lease a worker without a direct port
            if (w.direct_port is None
                    or not res_fits(resources, self.resources_available)):
                self._return_worker(w)
                break
            lease_id = uuid.uuid4().hex
            res_sub(self.resources_available, resources)
            w.lease_id = lease_id
            self._leases[lease_id] = {
                "worker": w, "resources": resources, "client_id": client_id,
            }
            granted.append({
                "lease_id": lease_id, "host": self.host,
                "port": w.direct_port, "worker_id": w.client_id,
            })
        # spillable: whether routing overflow through the raylet can reach
        # capacity BEYOND these leases — i.e. LIVE peer nodes exist (the
        # view retains dead nodes). On a single-node cluster a
        # constrained grant just means the local workers are the
        # bottleneck — the driver keeps the queue on its direct
        # pipelines instead of detouring it through us.
        peers_alive = sum(
            1 for nid, n in self.cluster_view.items()
            if n.alive and nid != self.node_id
        )
        return {"leases": granted, "spillable": peers_alive > 0}

    def rpc_task_events(self, conn: Connection, p):
        """Events from workers executing direct-push tasks; ride the
        raylet's batched flush to the GCS. Events carrying log offsets
        also feed the sender's span table, so the tailer can attribute
        streamed lines to task names."""
        w = self.workers_by_client.get(conn.meta.get("client_id"))
        if w is not None:
            for ev in p["events"]:
                _feed_log_span(w, ev)
        self._task_events.extend(p["events"])

    async def rpc_worker_fate(self, conn: Connection, p):
        cid = p["client_id"]
        if cid in self.workers_by_client:
            return {"alive": True, "reason": None}
        return {"alive": False, "reason": self._worker_fates.get(cid)}

    async def rpc_return_lease(self, conn: Connection, p):
        self._release_lease(p["lease_id"])
        return {}

    async def rpc_register_stored(self, conn: Connection, p):
        """A worker stored direct-task results into the node store: adopt
        them into this raylet's store view and publish locations (the
        raylet-routed path does this in _deliver_result; for direct push
        the executing worker self-reports, batched per tick)."""
        await self._register_stored_objects(p["object_ids"])
        return {}

    async def _register_stored_objects(self, oids):
        for oid in oids:
            # slab-resident results are accounted via slab_report; this
            # charges only one-file fallback writes (no-op otherwise)
            self.store.register_external(ObjectID(oid))
        if oids:
            await self._publish_locations(list(oids))

    def _release_lease(self, lease_id: str, worker_alive: bool = True):
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        w = lease["worker"]
        res_add(self.resources_available, lease["resources"])
        w.lease_id = None
        if (worker_alive and w.conn is not None and not w.conn.closed
                and self.workers_by_client.get(w.client_id) is w):
            self._return_worker(w)
        self._dispatch_event.set()

    def _reclaim_client_leases(self, client_id: str):
        """A driver died: return every lease it held."""
        for lease_id, lease in list(self._leases.items()):
            if lease["client_id"] == client_id:
                self._release_lease(lease_id)

    def _enqueue_actor_task(self, spec: TaskSpec, actor_addr):
        """Per-actor FIFO routing: enqueue SYNCHRONOUSLY (no awaits on any
        path to here) so queue order equals frame-arrival order, and drain
        with one router task per actor. Routing each task in its own
        dispatch task reorders them — concurrent wait_actor_alive awaits
        wake in arbitrary order, and the executor's seq gate then anchors
        on the wrong first arrival."""
        q = self._actor_route_queues.setdefault(spec.actor_id, deque())
        q.append((spec, actor_addr))
        if spec.actor_id not in self._actor_routers:
            self._actor_routers.add(spec.actor_id)
            spawn(
                self._actor_router(spec.actor_id)
            )

    async def rpc_submit_task(self, conn: Connection, p):
        spec: TaskSpec = p["spec"]
        if spec.actor_id is not None and not spec.actor_creation:
            self._enqueue_actor_task(spec, p.get("actor_addr"))
            return {}
        await self._schedule_or_queue(spec, depth=p.get("depth", 0))
        return {}

    async def rpc_submit_batch(self, conn: Connection, p):
        """Tick-batched submission: a driver flushing a burst sends ONE
        frame with N specs instead of N request round trips (ray parity:
        the core worker's task submission pipelining).

        Actor tasks are enqueued synchronously BEFORE the first await:
        a mid-batch await would let the next batch frame's handler run
        and enqueue its actor tasks first, reordering a single actor's
        calls across frames.

        ack="batch" (fire-and-forget lane): the reply acks frame
        ACCEPTANCE — scheduling proceeds in the background and the
        driver's await no longer spans per-spec placement. Failures past
        the ack surface exactly like failures past the legacy reply: via
        the owner-routed task_result stream and the task-event plane."""
        rest = []
        for spec in p["specs"]:
            if spec.actor_id is not None and not spec.actor_creation:
                self._enqueue_actor_task(spec, None)
            else:
                rest.append(spec)
        if p.get("ack") == "batch":
            if rest:
                t = asyncio.get_running_loop().create_task(
                    self._schedule_batch(rest)
                )
                self._bg_tasks.add(t)
                t.add_done_callback(self._bg_tasks.discard)
            return {"accepted": len(p["specs"])}
        for spec in rest:
            await self._schedule_or_queue(spec)
        return {}

    async def _schedule_batch(self, specs):
        """Background half of the batched-ack lane. The submitter already
        holds its ack, so a swallowed scheduling failure would hang its
        get() forever — every per-spec error is converted into an
        owner-routed task failure instead of a reply-path exception."""
        for spec in specs:
            try:
                await self._schedule_or_queue(spec)
            except Exception as e:  # noqa: BLE001
                logger.exception(
                    "background scheduling failed for %s",
                    spec.task_id.hex()[:16],
                )
                try:
                    await self._send_task_failure(
                        spec, f"task scheduling failed: {e!r}",
                        retriable=False,
                    )
                except Exception:
                    logger.exception(
                        "failed to surface scheduling failure for %s",
                        spec.task_id.hex()[:16],
                    )

    async def _actor_router(self, actor_id: bytes):
        """Drain one actor's routing queue sequentially (delivery order =
        submission order; execution concurrency is the executor's business,
        ray: CoreWorkerDirectActorTaskSubmitter's per-actor send queue)."""
        q = self._actor_route_queues[actor_id]
        try:
            while q:
                spec, actor_addr = q.popleft()
                try:
                    await self._route_actor_task(spec, actor_addr)
                except Exception as e:  # noqa: BLE001
                    # The submitter already got its {} reply: a swallowed
                    # routing failure would hang its ray.get forever.
                    logger.exception(
                        "routing actor task %s failed",
                        spec.task_id.hex()[:16],
                    )
                    try:
                        await self._send_task_failure(
                            spec, f"actor task routing failed: {e}",
                            retriable=True,
                        )
                    except Exception:
                        pass
        finally:
            self._actor_routers.discard(actor_id)
            if q:  # a task slipped in during the finally window
                if actor_id not in self._actor_routers:
                    self._actor_routers.add(actor_id)
                    spawn(
                        self._actor_router(actor_id)
                    )
            else:
                # drop the empty deque: actors churn, the dict must not
                # grow one entry per actor ever contacted
                self._actor_route_queues.pop(actor_id, None)

    async def rpc_spill_submit(self, conn: Connection, p):
        await self._schedule_or_queue(p["spec"], depth=p.get("depth", 0))
        return {}

    def rpc_spill_done(self, conn: Connection, p):
        """The node we spilled a task to reports it finished (or moved on):
        drop our resubmission liability."""
        key = (p["node_id"], p["task_id"])
        tracked = self._spilled_away.get(p["node_id"])
        if tracked and tracked.pop(p["task_id"], None) is not None:
            return
        # raced ahead of our own spill bookkeeping (chained re-spill can
        # settle before our spill_submit await resumes): tombstone it
        self._spill_released.add(key)
        if len(self._spill_released) > 10_000:  # bound pathological leaks
            self._spill_released.pop()

    async def _notify_spill_origin(self, spec: TaskSpec):
        """Tell the tracking node this task's fate is settled here."""
        origin = getattr(spec, "origin_node", None)
        if not origin or origin == self.node_id or spec.actor_id:
            return
        peer = await self._peer(origin)
        if peer is not None:
            try:
                await peer.notify(
                    "spill_done",
                    {"node_id": self.node_id, "task_id": spec.task_id},
                )
            except Exception:
                pass

    async def _schedule_or_queue(self, spec: TaskSpec, depth: int = 0):
        demand = spec.resources
        nodes = list(self.cluster_view.values())
        target = pick_node(nodes, demand, spec.scheduling, self.node_id, self._rr,
                           cfg.scheduler_spread_threshold)
        if target is None:
            # Infeasible now: queue locally, retried by dispatch loop.
            target = self.node_id
        if target != self.node_id and depth < cfg.max_spillback_depth:
            peer = await self._peer(target)
            if peer is not None:
                prev_origin = getattr(spec, "origin_node", None)
                spec.origin_node = self.node_id
                try:
                    # NO idem token here, deliberately: the handler itself
                    # chains spill_submit RPCs, and a task ping-ponging
                    # A->B->A->B reuses the same (task, attempt, sender)
                    # identity — dedup would make the second arrival await
                    # the first's still-running handler, a distributed
                    # deadlock. Wire-duplicate frames are already dropped
                    # by per-connection msg-id dedup, and this path never
                    # retries blindly (_spilled_away owns resubmission).
                    await peer.request(
                        "spill_submit", {"spec": spec, "depth": depth + 1}
                    )
                    self.counters["tasks_spilled"] += 1
                    # We now carry the resubmission liability for this task
                    # (normal tasks only: actor restarts are GCS-driven);
                    # the previous tracker is off the hook.
                    if not spec.actor_id:
                        key = (target, spec.task_id)
                        if key in self._spill_released:
                            # its fate settled before our await resumed
                            self._spill_released.discard(key)
                        else:
                            self._spilled_away.setdefault(target, {})[
                                spec.task_id
                            ] = spec
                        if prev_origin and prev_origin != self.node_id:
                            prev = await self._peer(prev_origin)
                            if prev is not None:
                                try:
                                    await prev.notify(
                                        "spill_done",
                                        {"node_id": self.node_id,
                                         "task_id": spec.task_id},
                                    )
                                except Exception:
                                    pass
                    return
                except Exception:
                    spec.origin_node = prev_origin
        self._queue_local(spec)

    def _queue_local(self, spec: TaskSpec):
        qt = _QueuedTask(spec, dict(spec.resources))
        missing = self._missing_deps(spec)
        if missing:
            qt.pending_deps = set(missing)
            self.waiting[spec.task_id] = qt
            self._emit_task_event(spec, "PENDING_ARGS_FETCH",
                                  missing=len(missing))
            for oid in missing:
                self.dep_waiters.setdefault(oid, []).append(spec.task_id)
                spawn(self._pull_for_dep(oid))
        else:
            qt.ready_at = time.perf_counter()
            self.ready.append(qt)
            self._emit_task_event(spec, "PENDING_NODE_ASSIGNMENT")
            self._dispatch_event.set()

    def _missing_deps(self, spec: TaskSpec) -> List[bytes]:
        missing = []
        for a in list(spec.args) + list(spec.kwargs.values()):
            if a[0] == "r":
                oid = a[1]
                if not self.store.contains(ObjectID(oid)):
                    missing.append(oid)
                    # remember the owner for the owner-first pull
                    if len(a) > 2 and a[2] is not None:
                        self.dep_owners.setdefault(oid, tuple(a[2]))
        return missing

    async def _pull_for_dep(self, oid: bytes):
        ok = await self._ensure_local(oid, priority=PULL_PRIO_TASK_ARGS,
                                      owner=self.dep_owners.pop(oid, None))
        waiters = self.dep_waiters.pop(oid, [])
        for tid in waiters:
            qt = self.waiting.get(tid)
            if qt is None:
                continue
            if not ok:
                del self.waiting[tid]
                await self._send_task_failure(
                    qt.spec, f"failed to fetch dependency {oid.hex()[:16]}",
                    retriable=True, lost_object=oid,
                )
                continue
            qt.pending_deps.discard(oid)
            if not qt.pending_deps:
                del self.waiting[tid]
                qt.ready_at = time.perf_counter()
                self.ready.append(qt)
                self._dispatch_event.set()

    # ------------------------------------------------------------------
    # dispatch loop (LocalTaskManager)
    # ------------------------------------------------------------------
    async def _dispatch_loop(self):
        """Per-wakeup cost is O(classes + dispatched), NOT O(queue):
        the ready structure keys FIFOs by scheduling class (ray:
        cluster_task_manager.cc keys its queues by SchedulingClass), so
        when a class's head task doesn't fit, the entire class is skipped
        in O(1). A flat deque scanned with a blocked-set still cost
        O(queue) pop/append churn per wakeup — profiled at 3.7M deque ops
        for a 3k-task burst."""
        while True:
            await self._dispatch_event.wait()
            self._dispatch_event.clear()
            retry = False
            pool_exhausted = False
            for cls in list(self.ready.by_cls.keys()):
                while not pool_exhausted:
                    q = self.ready.by_cls.get(cls)
                    if not q:
                        break
                    qt = self.ready.pop_head(cls)
                    if not res_fits(qt.resources, self.resources_available):
                        # Infeasible on this node entirely: park it in the
                        # explicit infeasible queue — visible to the demand
                        # report (autoscaler scale-up) and retried when the
                        # cluster gains capacity (ray: ClusterTaskManager's
                        # infeasible queue, reported to GCS). Else this
                        # class waits for local resources to free up.
                        if not res_fits(qt.resources, self.resources_total):
                            self.infeasible[qt.spec.task_id] = qt
                            continue
                        self.ready.push_front(qt)
                        retry = True
                        break
                    w = await self._pop_worker(qt.spec)
                    if w is None:
                        # worker-pool soft limit: a global condition — no
                        # class gets a worker this pass
                        self.ready.push_front(qt)
                        retry = True
                        pool_exhausted = True
                        break
                    if not res_fits(qt.resources, self.resources_available):
                        # a concurrent lease grant (rpc_lease_workers) may
                        # have reserved these resources during the await
                        self._return_worker(w)
                        self.ready.push_front(qt)
                        retry = True
                        break
                    res_sub(self.resources_available, qt.resources)
                    qt.worker = w
                    w.busy_with = qt.spec.task_id
                    self.running[qt.spec.task_id] = qt
                    self.counters["tasks_dispatched"] += 1
                    if qt.ready_at:
                        self._placement_lat.record(
                            time.perf_counter() - qt.ready_at)
                    spawn(
                        self._run_on_worker(qt, w)
                    )
            if retry:
                # Re-arm WITHOUT blocking this loop: a completing task sets
                # the event and must be dispatched to immediately — sleeping
                # inline here capped throughput at workers/interval
                # (~400 tasks/s at 4 workers x 10ms). The timer is only the
                # fallback for conditions no completion will signal.
                asyncio.get_running_loop().call_later(
                    cfg.dispatch_retry_interval_s, self._dispatch_event.set
                )

    async def _infeasible_retry_loop(self):
        """Re-run cluster scheduling for parked infeasible tasks once some
        node's total capacity could fit them (a new node joined, a PG
        bundle committed). A reschedule failure re-parks the task — one
        dying peer must not kill the loop or drop the task."""
        while True:
            await asyncio.sleep(cfg.infeasible_retry_interval_s)
            if not self.infeasible:
                continue
            for tid, qt in list(self.infeasible.items()):
                if not any(
                    n.alive and res_fits(qt.resources, n.resources_total)
                    for n in self.cluster_view.values()
                ):
                    continue
                del self.infeasible[tid]
                try:
                    await self._schedule_or_queue(qt.spec, depth=0)
                except Exception:
                    logger.exception(
                        "rescheduling infeasible task %s failed; re-parking",
                        tid.hex()[:16],
                    )
                    self.infeasible.setdefault(tid, qt)

    async def _run_on_worker(self, qt: _QueuedTask, w: _Worker):
        # provisional open span at the file's current end: the worker
        # measures the exact range (its buffers flushed) and reports it
        # with the result — closed spans override this for attribution
        extra = {}
        if w.log_path:
            try:
                start = os.path.getsize(w.log_path)
            except OSError:
                start = None
            if start is not None:
                extra = {"log_file": os.path.basename(w.log_path),
                         "log_start": start}
                w.log_spans.open_span(qt.spec.task_id.hex(), qt.spec.name,
                                      start)
        self._emit_task_event(qt.spec, "RUNNING", pid=w.proc.pid, **extra)
        try:
            # timeout=0 (unbounded): this await spans the USER CODE's whole
            # runtime — a deadline here would falsely kill long tasks and
            # double-execute them on retry. Keepalive covers the dead-peer
            # case the default deadline exists for.
            result = await w.conn.request("execute_task", {"spec": qt.spec},
                                          timeout=0)
        except Exception as e:
            result = None
            logger.warning("dispatch to worker failed: %s", e)
        # If the worker died, _on_worker_conn_lost already popped the task and
        # returned its resources — only release them if we pop it ourselves.
        popped = self.running.pop(qt.spec.task_id, None)
        if popped is not None:
            res_add(self.resources_available, qt.resources)
        w.busy_with = None
        if result is None:
            # worker died; _on_worker_conn_lost handles failure notification.
            self._dispatch_event.set()
            return
        if w.actor_id is None and not w.conn.closed:
            self._return_worker(w)
        span = result.get("log_span")
        if span:
            extra = {"log_file": span["file"], "log_start": span["start"],
                     "log_end": span["end"]}
            w.log_spans.close_span(qt.spec.task_id.hex(), qt.spec.name,
                                   span["start"], span["end"])
        else:
            extra = {}
            w.log_spans.discard(qt.spec.task_id.hex())
        if result.get("error") is not None:
            self._emit_task_event(qt.spec, "FAILED", pid=w.proc.pid,
                                  error=str(result.get("error"))[:200],
                                  **extra)
        else:
            self._emit_task_event(qt.spec, "FINISHED", pid=w.proc.pid,
                                  duration=result.get("duration"), **extra)
        await self._deliver_result(qt.spec, result)
        self._dispatch_event.set()

    async def _deliver_result(self, spec: TaskSpec, result: dict):
        """Route a completed task's result notification to the owner."""
        await self._register_stored_objects(result.get("stored_objects", ()))
        payload = {
            "task_id": spec.task_id,
            "results": result.get("results"),
            "error": result.get("error"),
            "error_value": result.get("error_value"),
            "app_error": result.get("app_error", False),
            "retriable": result.get("retriable", False),
            "attempt": spec.attempt,
            # borrower-protocol fields (ray: reference_count.h borrowed_refs
            # reported in PushTaskReply)
            "exec_addr": result.get("exec_addr"),
            "borrows_kept": result.get("borrows_kept"),
            "returns_nested": result.get("returns_nested"),
            # num_returns="dynamic": item objects the owner must adopt
            "dynamic_return_oids": result.get("dynamic_return_oids"),
        }
        await self._route_to_owner(spec.owner, "task_result", payload)
        await self._notify_spill_origin(spec)

    async def _route_to_owner(self, owner: tuple, method: str, payload):
        node_id, client_id = owner
        if method == "task_result":
            # tick-batch: a burst of completions becomes ONE frame per
            # owner (same discipline as submit_batch on the way in)
            self._owner_outbox.setdefault((node_id, client_id), []).append(
                payload
            )
            if not self._owner_flushing:
                self._owner_flushing = True
                spawn(
                    self._flush_owner_outbox()
                )
            return
        await self._send_to_owner(node_id, client_id, method, payload)

    async def _flush_owner_outbox(self):
        await asyncio.sleep(0)  # one tick: let same-burst completions land
        outbox, self._owner_outbox = self._owner_outbox, {}
        self._owner_flushing = False
        for (node_id, client_id), payloads in outbox.items():
            if len(payloads) == 1:
                await self._send_to_owner(
                    node_id, client_id, "task_result", payloads[0]
                )
            else:
                await self._send_to_owner(
                    node_id, client_id, "task_result_batch", payloads
                )

    async def _send_to_owner(self, node_id, client_id, method: str, payload):
        if node_id == self.node_id:
            conn = self.clients.get(client_id)
            if conn is not None and not conn.closed:
                try:
                    await conn.notify(method, payload)
                except Exception:
                    pass
            return
        peer = await self._peer(node_id)
        if peer is not None:
            try:
                await peer.notify(
                    "route_to_client",
                    {"client_id": client_id, "method": method, "payload": payload},
                )
            except Exception:
                pass

    async def rpc_route_to_client(self, conn: Connection, p):
        c = self.clients.get(p["client_id"])
        if c is not None and not c.closed:
            try:
                await c.notify(p["method"], p["payload"])
            except Exception:
                pass

    async def _send_task_failure(self, spec: TaskSpec, reason: str, retriable: bool,
                                 lost_object: Optional[bytes] = None,
                                 worker_died: bool = False):
        await self._route_to_owner(
            spec.owner,
            "task_result",
            {"task_id": spec.task_id, "results": None, "error": reason,
             "system_error": True, "retriable": retriable, "attempt": spec.attempt,
             "lost_object": lost_object, "worker_died": worker_died},
        )
        await self._notify_spill_origin(spec)

    # ------------------------------------------------------------------
    # worker pool
    # ------------------------------------------------------------------
    def _return_worker(self, w: _Worker):
        self.idle_workers.setdefault(w.env_hash, deque()).append(w)

    async def _pop_worker(self, spec: TaskSpec) -> Optional[_Worker]:
        return await self._pop_worker_for(spec.job_id, spec.runtime_env)

    async def _pop_worker_for(self, job_id: Optional[bytes],
                              runtime_env: Optional[dict]) -> Optional[_Worker]:
        env_hash = runtime_env_hash(runtime_env)
        waited_s = 0.0
        while True:
            pool = self.idle_workers.get(env_hash)
            while pool:
                w = pool.popleft()
                if w.conn is not None and not w.conn.closed:
                    return w
            # A same-env worker is mid-boot (prestart or a concurrent
            # request): wait for it instead of racing a duplicate multi-
            # second interpreter spawn — but only as many waiters as
            # there are boots in flight, so N genuinely-concurrent
            # requests still spawn N workers in parallel.
            starting = self._workers_starting.get(env_hash, 0)
            waiting = self._spawn_waiters.get(env_hash, 0)
            if starting <= waiting:
                break
            if waited_s > cfg.worker_register_timeout_s * 2:
                # Livelock breaker: no boot takes this long — a leaked
                # _workers_starting count would otherwise park every
                # lease/dispatch for this env forever. Spawn our own.
                logger.error(
                    "spawn-wait exceeded %.0fs (starting=%d waiting=%d "
                    "env=%s); breaking out to spawn directly",
                    waited_s, starting, waiting, env_hash[:8],
                )
                break
            self._spawn_waiters[env_hash] = waiting + 1
            try:
                await asyncio.wait_for(self._worker_started.wait(), 0.25)
            except asyncio.TimeoutError:
                pass
            finally:
                self._spawn_waiters[env_hash] -= 1
            waited_s += 0.25
            self._worker_started.clear()
        n_alive = len(self.all_workers)
        if n_alive >= cfg.num_workers_soft_limit:
            # Reclaim ONE idle worker of a different runtime env to free a slot.
            for other in self.idle_workers.values():
                reclaimed = False
                while other:
                    victim = other.popleft()
                    if victim.conn is not None and not victim.conn.closed:
                        victim.kill_intended = True
                        victim.proc.terminate()
                        reclaimed = True
                        break
                if reclaimed:
                    break
            return None
        return await self._start_worker(job_id, runtime_env)

    async def _start_worker(self, job_id: Optional[bytes],
                            runtime_env: Optional[dict] = None) -> Optional[_Worker]:
        from ray_tpu._private.node import package_env

        env = package_env()
        if runtime_env:
            for k, v in (runtime_env.get("env_vars") or {}).items():
                env[k] = str(v)
            if env.get("JAX_PLATFORMS") == "cpu":
                # the runtime_env pinned this worker to CPU after
                # package_env's stash restore ran: drop the TPU-plugin
                # trigger so the worker skips sitecustomize's jax import
                env.pop("PALLAS_AXON_POOL_IPS", None)
        env["RAY_TPU_NODE_ID"] = self.node_id
        # explicit spawn key (RAY_TPU_ prefix rides the container env
        # filter): the worker echoes it in register_client so the match
        # works even when the engine translates pids
        import uuid as _uuid

        spawn_id = _uuid.uuid4().hex
        env["RAY_TPU_WORKER_SPAWN_ID"] = spawn_id
        # workers bind their direct-push server to the same host the
        # raylet advertises in lease grants and actor direct_addrs
        env["RAY_TPU_NODE_IP"] = self.host
        env["RAY_TPU_RAYLET_PORT"] = str(self.port)
        env["RAY_TPU_GCS_ADDR"] = f"{self.gcs_host}:{self.gcs_port}"
        env["RAY_TPU_STORE_DIR"] = self.store_dir
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        if runtime_env:
            # ship every key except env_vars (already applied at spawn,
            # above) so the worker's plugin registry — built-ins AND
            # custom plugins — can materialize it before serving tasks
            # (ray: raylet -> runtime-env agent CreateRuntimeEnv).
            import json as _json

            to_ship = {k: v for k, v in runtime_env.items()
                       if k != "env_vars" and v is not None}
            if to_ship:
                try:
                    env["RAY_TPU_RUNTIME_ENV"] = _json.dumps(to_ship)
                except TypeError:
                    # defense in depth (the driver validates at option
                    # time): a non-JSON value must not kill the dispatch
                    # loop — ship the safe subset and log loudly
                    safe = {}
                    for k, v in to_ship.items():
                        try:
                            _json.dumps(v)
                            safe[k] = v
                        except TypeError:
                            logger.error(
                                "runtime_env[%r] is not JSON-serializable; "
                                "dropped for worker spawn", k,
                            )
                    if safe:
                        env["RAY_TPU_RUNTIME_ENV"] = _json.dumps(safe)
        # Workers must not grab the TPU unless a task asks for it; JAX inits
        # lazily so this is safe, but keep workers on CPU by default for
        # control-plane work (the trainer backend overrides per worker group).
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        self._worker_seq = getattr(self, "_worker_seq", 0) + 1
        log_file = os.path.join(
            log_dir,
            f"worker-{self.node_id[:8]}-{self._worker_seq}.out",
        )
        # the worker measures its own log offsets around user code for
        # per-task attribution (logplane.stdio_offset); RAY_TPU_ prefix
        # rides the container env filter like the spawn id does
        env["RAY_TPU_WORKER_LOG_FILE"] = log_file
        argv = [sys.executable, "-m", "ray_tpu._private.worker_main"]
        cidfile = None
        container = (runtime_env or {}).get("container")
        if container is not None and (
            not isinstance(container, dict) or not container.get("image")
        ):
            # defense in depth: the driver validates at option time, but a
            # hand-built spec must not crash the dispatch loop
            logger.error(
                "invalid runtime_env['container'] %r: expected a dict with "
                "'image'; refusing to spawn", container,
            )
            return None
        if container:
            # container plugin (ray parity: runtime_env/container.py):
            # the worker process runs INSIDE the image; host network/ipc/
            # pid namespaces and /dev/shm shared so control plane, data
            # plane, and pid-keyed registration are unchanged. The
            # cidfile lets us force-remove the container if we have to
            # kill the engine client (SIGKILL never proxies inside).
            from ray_tpu._private.runtime_env import build_container_command

            cidfile = os.path.join(
                log_dir, f"container-{self.node_id[:8]}-{self._worker_seq}.cid"
            )
            env_var_keys = tuple((runtime_env or {}).get("env_vars") or ())
            argv = build_container_command(
                container, env,
                ["python", "-m", "ray_tpu._private.worker_main"],
                extra_env_keys=env_var_keys + ("PALLAS_AXON_POOL_IPS",),
                cidfile=cidfile,
            )
        proc = subprocess.Popen(
            argv,
            env=env,
            stdout=open(log_file, "ab"),
            stderr=subprocess.STDOUT,
        )
        w = _Worker(proc, job_id, env_hash=runtime_env_hash(runtime_env),
                    log_path=log_file, cidfile=cidfile,
                    engine=(container.get("engine") or cfg.container_runtime)
                    if container else None, spawn_id=spawn_id)
        self.all_workers[proc.pid] = w
        self._workers_by_spawn[spawn_id] = w
        ehash = w.env_hash
        self._workers_starting[ehash] = \
            self._workers_starting.get(ehash, 0) + 1
        logger.info("spawning worker pid=%s env=%s (starting=%d)",
                    proc.pid, ehash[:8], self._workers_starting[ehash])
        try:
            await asyncio.wait_for(w.registered, cfg.worker_register_timeout_s)
        except asyncio.TimeoutError:
            logger.error(
                "worker %s failed to register within %.0fs (proc %s)",
                proc.pid, cfg.worker_register_timeout_s,
                "alive" if proc.poll() is None
                else f"exited rc={proc.returncode}",
            )
            w.kill_process()  # reaches the container too, if any
            self.all_workers.pop(proc.pid, None)
            self._workers_by_spawn.pop(spawn_id, None)
            return None
        finally:
            self._workers_starting[ehash] -= 1
            self._worker_started.set()
        logger.info("worker pid=%s registered", proc.pid)
        return w

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    async def rpc_create_actor(self, conn: Connection, p):
        spec: TaskSpec = p["spec"]
        # App-level idempotency: a retried creation (the reply to the first
        # attempt was lost in flight, or the caller's deadline expired while
        # the worker was still spawning) must join the live/in-flight
        # creation, not spawn a second worker for the same actor_id. This
        # is the dedup layer for create_actor — an rpc-level idem token is
        # wrong here because the scheduler legitimately re-asks after
        # transient rejections, and a cached {"rejected"} would poison
        # every later attempt on this node.
        w = self.local_actors.get(spec.actor_id)
        if w is not None and w.conn is not None and not w.conn.closed:
            return {"worker_client_id": w.client_id,
                    "direct_addr": (self.host, w.direct_port)
                    if w.direct_port else None}
        pending = self._actors_creating.get(spec.actor_id)
        if pending is not None:
            # a retry racing the in-flight creation shares its outcome
            # (resolved with a reply dict, never an exception)
            return await asyncio.shield(pending)
        fut = asyncio.get_running_loop().create_future()
        self._actors_creating[spec.actor_id] = fut
        reply = {"rejected": True}
        try:
            reply = await self._do_create_actor(spec)
            return reply
        finally:
            self._actors_creating.pop(spec.actor_id, None)
            if not fut.done():
                fut.set_result(reply)

    async def _do_create_actor(self, spec: TaskSpec) -> dict:
        if not res_fits(spec.resources, self.resources_available):
            return {"rejected": True}
        w = await self._pop_worker(spec)
        if w is None:
            return {"rejected": True}
        res_sub(self.resources_available, spec.resources)
        try:
            reply = await w.conn.request("become_actor", {"spec": spec},
                                         timeout=cfg.gcs_rpc_timeout_s)
        except Exception as e:
            res_add(self.resources_available, spec.resources)
            return {"rejected": True, "detail": str(e)}
        if reply.get("error"):
            res_add(self.resources_available, spec.resources)
            self._return_worker(w)
            return {"error": reply["error"]}
        w.actor_id = spec.actor_id
        w.actor_resources = dict(spec.resources)
        # streamed-line fallback prefix: anything this worker prints
        # outside a method's span attributes to the actor class
        w.log_name = spec.name
        self.local_actors[spec.actor_id] = w
        return {"worker_client_id": w.client_id,
                "direct_addr": (self.host, w.direct_port)
                if w.direct_port else None}

    async def rpc_kill_actor(self, conn: Connection, p):
        w = self.local_actors.get(p["actor_id"])
        if w is None:
            return {}
        w.kill_intended = True
        res_add(self.resources_available, getattr(w, "actor_resources", {}))
        try:
            w.proc.terminate()
        except Exception:
            pass
        return {}

    async def _route_actor_task(self, spec: TaskSpec, actor_addr: Optional[tuple]):
        # Local actor: push straight to its worker.
        w = self.local_actors.get(spec.actor_id)
        if w is not None and w.conn is not None and not w.conn.closed:
            spawn(self._run_actor_task(spec, w))
            return
        addr = actor_addr or self.actor_addr_cache.get(spec.actor_id)
        if addr is None or addr[0] == self.node_id:
            try:
                table = await self.gcs.request(
                    "wait_actor_alive",
                    {"actor_id": spec.actor_id,
                     "timeout": cfg.actor_route_wait_alive_timeout_s}
                )
            except Exception:
                table = None
            if table is None or table["state"] == "DEAD" or not table.get("address"):
                await self._route_to_owner(
                    spec.owner, "task_result",
                    {"task_id": spec.task_id, "results": None,
                     "error": f"actor {spec.actor_id.hex()[:16]} is dead"
                     if table and table["state"] == "DEAD" else "actor unavailable",
                     "actor_dead": bool(table and table["state"] == "DEAD"),
                     "system_error": True, "retriable": False, "attempt": spec.attempt},
                )
                return
            addr = tuple(table["address"])
        self.actor_addr_cache[spec.actor_id] = addr
        if addr[0] == self.node_id:
            await self._route_actor_task(spec, None)
            return
        peer = await self._peer(addr[0])
        if peer is None:
            self.actor_addr_cache.pop(spec.actor_id, None)
            await self._send_task_failure(spec, "actor node unreachable", retriable=True)
            return

        # Forward WITHOUT awaiting the round trip: the per-actor router
        # must not serialize throughput to one task per RTT. In-order
        # sends are enough for ordering (the remote enqueues synchronously
        # on dispatch); the tracked task handles a failed forward.
        async def _forward():
            try:
                await peer.request(
                    "submit_task", {"spec": spec, "actor_addr": addr}
                )
            except Exception:
                self.actor_addr_cache.pop(spec.actor_id, None)
                await self._send_task_failure(
                    spec, "actor node unreachable", retriable=True
                )

        spawn(_forward())

    async def _run_actor_task(self, spec: TaskSpec, w: _Worker):
        try:
            # timeout=0: spans the actor method's runtime (see dispatch path)
            result = await w.conn.request("execute_task", {"spec": spec},
                                          timeout=0)
        except Exception:
            # actor worker died mid-task; GCS failure path notifies owner of
            # actor death; report retriable failure for this call.
            await self._send_task_failure(spec, "actor worker died",
                                          retriable=True, worker_died=True)
            return
        await self._deliver_result(spec, result)

    # ------------------------------------------------------------------
    # object plane
    # ------------------------------------------------------------------
    async def rpc_register_put(self, conn: Connection, p):
        oid = p["object_id"]
        self.store.register_external(ObjectID(oid))
        try:
            await self.gcs.request(
                "add_object_location", {"object_id": oid, "node_id": self.node_id}
            )
        except Exception:
            pass
        return {}

    # -- slab arena lease + batched accounting (slab_arena.py) ---------
    async def rpc_lease_slab(self, conn: Connection, p):
        """Grant a write slab to a local client (one RPC amortized over
        many puts); ``seal`` retires the caller's previous slab in the
        same round trip. A denial (no arena / store full of leased
        slabs) sends the writer to the one-file fallback path, whose
        register_external accounts the overshoot honestly."""
        lease = getattr(self.store, "lease_slab", None)
        if lease is None:
            return {"ok": False}
        seals = p.get("seals") or ([p["seal"]] if p.get("seal") else [])
        return lease(conn.meta.get("client_id") or "", int(p["bytes"]),
                     seals)

    async def rpc_slab_report(self, conn: Connection, p):
        """Batched put accounting from a slab writer: adopt the entries
        into the store ledger and publish the new locations to the GCS
        in ONE frame (vs the legacy one-register_put-RPC-per-put)."""
        record = getattr(self.store, "record_slab_objects", None)
        if record is None:
            return {}
        new = record(p["objects"])
        if new:
            await self._publish_locations(new)
            self._dispatch_event.set()
        return {}

    def _reclaim_client_slabs(self, client_id: str):
        """A slab-leasing client died: adopt the sealed prefixes of its
        leased segments (torn mid-put tails are discarded by the scan)
        and publish any unreported objects it managed to seal."""
        reclaim = getattr(self.store, "reclaim_client_slabs", None)
        if reclaim is None or not client_id:
            return
        try:
            new = reclaim(client_id)
        except Exception:
            logger.exception("slab reclaim for %s failed", client_id[:8])
            return
        if new:
            t = spawn(self._publish_locations(new))
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)

    async def _publish_locations(self, oids):
        try:
            await self.gcs.request(
                "add_object_locations",
                {"object_ids": list(oids), "node_id": self.node_id},
            )
        except Exception:
            pass  # directory is best-effort; owner locations self-heal

    async def rpc_pull_object(self, conn: Connection, p):
        owner = p.get("owner")
        ok = await self._ensure_local(
            p["object_id"], timeout=p.get("timeout"),
            priority=p.get("priority", PULL_PRIO_GET),
            owner=tuple(owner) if owner else None,
        )
        return {"ok": ok}

    async def _ensure_local(self, oid_bytes: bytes,
                            timeout: Optional[float] = None,
                            priority: int = PULL_PRIO_GET,
                            owner: Optional[tuple] = None) -> bool:
        oid = ObjectID(oid_bytes)
        if self.store.contains(oid):
            # May be spilled: bring it back into shm so workers can mmap it.
            restore = getattr(self.store, "restore_if_spilled", None)
            if restore is not None:
                restore(oid)
            return True
        fut = self._pulls_inflight.get(oid_bytes)
        if fut is not None:
            ok = await fut
            if ok or owner is None:
                return ok
            # the coalesced pull may have lacked our owner hint (e.g. an
            # ownerless pull racing a dep pull during a GCS outage): try
            # once more owner-aware now that the failed pull is cleared
            if self._pulls_inflight.get(oid_bytes) is None:
                return await self._ensure_local(
                    oid_bytes, timeout=timeout, priority=priority,
                    owner=owner,
                )
            return ok
        fut = asyncio.get_running_loop().create_future()
        self._pulls_inflight[oid_bytes] = fut
        try:
            await self._pull_gate.acquire(priority)
            try:
                ok = await self._do_pull(oid, timeout=timeout, owner=owner)
            finally:
                self._pull_gate.release_slot()
            # an incoming push may have satisfied (and resolved) us already
            if not fut.done():
                fut.set_result(ok)
            return fut.result()
        except Exception as e:
            if not fut.done():
                fut.set_result(False)
            logger.warning("pull of %s failed: %s", oid_bytes.hex()[:16], e)
            return False
        finally:
            self._pulls_inflight.pop(oid_bytes, None)

    async def _do_pull(self, oid: ObjectID, timeout: Optional[float] = None,
                       owner: Optional[tuple] = None) -> bool:
        """Resolve locations OWNER-FIRST (ray:
        ownership_based_object_directory.h): the owning worker is the
        authority on where its object has copies; the GCS directory is
        only the bootstrap/cache fallback. Pulls therefore keep working
        through a GCS outage or restart whenever the caller knows the
        owner (task args and driver gets do)."""
        deadline = time.monotonic() + (timeout or cfg.object_pull_timeout_s)
        while time.monotonic() < deadline:
            owner_locs: list = []
            if owner is not None:
                owner_locs = await self._query_owner_locations(
                    owner, oid, deadline
                )
            # Merge rather than short-circuit: a stale owner entry (no
            # removal protocol on eviction) must not shadow a live copy
            # the GCS knows about. A dead GCS just contributes nothing.
            locs = list(owner_locs)
            try:
                gcs_locs = await self.gcs.request(
                    "get_object_locations",
                    {"object_id": oid.binary(), "wait": not owner_locs,
                     "timeout": max(0.1, min(5.0,
                                             deadline - time.monotonic()))},
                )
                locs.extend(l for l in gcs_locs if l not in locs)
            except Exception:
                pass
            locs = [l for l in locs if l != self.node_id]
            if not locs and self.store.contains(oid):
                return True
            for node_id in locs:
                peer = await self._peer(node_id)
                info = self.cluster_view.get(node_id)
                same_host = info is not None and info.host == self.host
                if peer is not None and await self._fetch_from(
                        peer, oid, same_host=same_host):
                    self.counters["objects_pulled"] += 1
                    if node_id in owner_locs:
                        self.counters["owner_location_hits"] = (
                            self.counters.get("owner_location_hits", 0) + 1
                        )
                    if owner is not None:
                        await self._send_to_owner(
                            owner[0], owner[1], "owner_add_location",
                            {"object_id": oid.binary(),
                             "node_id": self.node_id},
                        )
                    try:
                        # retried (idempotent): a dropped registration would
                        # leave the new copy invisible to the directory
                        await call_with_retries(
                            lambda: self.gcs, "add_object_location",
                            {"object_id": oid.binary(),
                             "node_id": self.node_id},
                        )
                    except Exception:
                        pass
                    return True
                if owner is not None and node_id in owner_locs:
                    # unreachable/empty copy: retract the stale entry so
                    # the owner directory converges
                    await self._send_to_owner(
                        owner[0], owner[1], "owner_remove_location",
                        {"object_id": oid.binary(), "node_id": node_id},
                    )
            if self.store.contains(oid):
                return True
            await asyncio.sleep(cfg.pull_location_poll_interval_s)
        return False

    async def _query_owner_locations(self, owner: tuple, oid: ObjectID,
                                     deadline: float) -> list:
        # cap by the pull deadline: this runs while holding a pull-gate
        # slot, so a half-open owner connection must not starve the gate
        # for a full RPC timeout per attempt
        budget = max(0.1, min(cfg.gcs_rpc_timeout_s,
                              deadline - time.monotonic()))
        node_id, client_id = tuple(owner)
        try:
            if node_id == self.node_id:
                conn = self.clients.get(client_id)
                if conn is None or conn.closed:
                    return []
                reply = await conn.request(
                    "object_locations", {"object_id": oid.binary()},
                    timeout=budget,
                )
            else:
                peer = await self._peer(node_id)
                if peer is None:
                    return []
                reply = await peer.request(
                    "owner_locations",
                    {"client_id": client_id, "object_id": oid.binary()},
                    timeout=budget,
                )
            return list(reply.get("locations") or [])
        except Exception:
            return []

    async def rpc_owner_locations(self, conn: Connection, p):
        """Peer raylet resolving an owner that is OUR local client."""
        c = self.clients.get(p["client_id"])
        if c is None or c.closed:
            return {"locations": []}
        try:
            return await c.request(
                "object_locations", {"object_id": p["object_id"]},
                timeout=cfg.gcs_rpc_timeout_s,
            )
        except Exception:
            return {"locations": []}

    async def _fetch_from(self, peer: Connection, oid: ObjectID,
                          same_host: bool = False) -> bool:
        """Pull one object from a peer: the first chunk reveals the total
        size and metadata; the rest are fetched through a bounded window
        of CONCURRENT chunk requests (a serial chunk loop is latency-
        bound — the reason push used to outrun pull) that land
        out-of-order at their offsets. With a slab-backed store the
        chunks pwrite straight into a reserved unsealed arena entry
        (receive-side slab assembly: no heap staging, no store-put copy)
        sealed by the atomic state-word flip only when every byte has
        arrived; otherwise they assemble in heap buffers as before.

        ``same_host`` collapses the request window to 1: loopback peers
        have no RTT to hide, so concurrent frames on one connection only
        contend for CPU — the net-read-overlaps-pwrite pipelining below
        still applies (the measured win on single-host clusters)."""
        chunk = cfg.object_transfer_chunk_bytes
        head = max(1, min(chunk, cfg.fetch_head_chunk_bytes))
        t0 = time.perf_counter()
        try:
            first = await peer.request(
                "fetch_object",
                {"object_id": oid.binary(), "offset": 0, "chunk": head},
                timeout=cfg.gcs_rpc_timeout_s,
            )
        except Exception:
            return False
        if not first.get("exists"):
            return False
        total = first["total"]
        metadata = first["metadata"]
        # Byte-budget admission: now that the size is known, reserve it so
        # concurrent pulls cannot together overrun the transfer budget.
        await self._pull_gate.charge(total)
        res = None
        sealed = False
        try:
            data0 = first["data"]
            reserve = getattr(self.store, "reserve", None)
            if reserve is not None:
                res = reserve(oid, metadata, total)
            parts: Optional[dict] = None if res is not None else {}
            received = [0]
            failed = [False]
            loop = asyncio.get_running_loop()
            land_lock = asyncio.Lock()

            async def land(off, data):
                if res is not None:
                    # pwrite on an executor thread (os.pwrite drops the
                    # GIL): the event loop keeps decoding the next
                    # in-flight chunk's frame while this one lands —
                    # without this, chunk writes serialize behind frame
                    # reads and the pipeline buys nothing. Landings are
                    # SERIALIZED with each other (one pwrite at a time):
                    # parallel multi-MB pwrites just fight the socket
                    # reads for memory bandwidth
                    async with land_lock:
                        await loop.run_in_executor(None, res.write, off,
                                                   data)
                else:
                    parts[off] = data
                received[0] += len(data)

            try:
                await land(0, data0)
            except (ValueError, OSError):
                # same contract as the per-chunk guard in fetch_one: an
                # arena-landing failure (ENOSPC at first touch) fails
                # THIS attempt — the finally abandons the reservation,
                # and the retry's reserve() degrades to heap assembly
                return False
            if received[0] < total:
                depth = 1 if same_host else cfg.fetch_pipeline_depth
                sem = asyncio.Semaphore(max(1, depth))

                async def fetch_one(off):
                    try:
                        nxt = await peer.request(
                            "fetch_object",
                            {"object_id": oid.binary(), "offset": off,
                             "chunk": chunk},
                            timeout=cfg.gcs_rpc_timeout_s,
                        )
                        data = nxt["data"] if nxt.get("exists") else None
                    except Exception:
                        data = None
                    finally:
                        # the slot guards NETWORK in-flight only: freeing
                        # it at arrival lets the next chunk's socket read
                        # overlap this chunk's pwrite (the landing queue
                        # stays ~1 deep — pwrite outruns the wire)
                        sem.release()
                    if data is None or len(data) != min(chunk, total - off):
                        failed[0] = True
                        return
                    try:
                        await land(off, data)
                    except (ValueError, OSError):
                        failed[0] = True

                pending = []
                for off in range(len(data0), total, chunk):
                    await sem.acquire()
                    if failed[0]:
                        sem.release()
                        break  # stop issuing into a failed transfer
                    pending.append(spawn(fetch_one(off)))
                await asyncio.gather(*pending, return_exceptions=True)
            if failed[0] or received[0] != total:
                return False
            if res is not None:
                sealed = res.seal()
                if not sealed:
                    return False
                path = "arena"
            else:
                self.store.put(oid, metadata,
                               [parts[k] for k in sorted(parts)], total)
                # "heap": chunks staged through heap buffers before the
                # store-put copy (legacy/native fallback only)
                path = "heap"
            from ray_tpu._private import memview

            memview.record_flow("fetch", total,
                                time.perf_counter() - t0, path,
                                oid.hex())
            return True
        finally:
            if res is not None and not sealed:
                res.abandon()
            self._pull_gate.uncharge(total)

    # ------------------------------------------------------------------
    # push plane (ray: object_manager/push_manager.h:30 — owner/holder-
    # initiated transfer with per-peer chunk budgets and dedup, vs the
    # receiver-driven pull path above) + tree broadcast
    # ------------------------------------------------------------------
    async def push_object(self, oid: ObjectID, node_id: str) -> bool:
        """Push a locally-present object to one peer. Dedup: a second push
        of the same (object, peer) while one is in flight piggybacks on
        it; chunk sends share a bounded per-peer pipeline."""
        key = (oid.binary(), node_id)
        existing = self._pushes_inflight.get(key)
        if existing is not None:
            return await asyncio.shield(existing)
        fut = asyncio.get_running_loop().create_future()
        self._pushes_inflight[key] = fut
        ok = False
        try:
            ok = await self._do_push(oid, node_id)
        except Exception as e:  # noqa: BLE001
            logger.warning("push of %s to %s failed: %s",
                           oid.hex()[:16], node_id[:8], e)
        finally:
            # resolve in the finally: if this task is CANCELLED mid-push,
            # piggybacked pushers shielded on `fut` must not hang forever
            self._pushes_inflight.pop(key, None)
            if not fut.done():
                fut.set_result(ok)
        if ok:
            self.counters["objects_pushed"] = (
                self.counters.get("objects_pushed", 0) + 1
            )
        return ok

    async def _do_push(self, oid: ObjectID, node_id: str) -> bool:
        peer = await self._peer(node_id)
        if peer is None:
            return False
        buf = self.store.get(oid)
        if buf is None:
            return False
        t0 = time.perf_counter()
        try:
            total = len(buf.data)
            chunk = cfg.object_transfer_chunk_bytes
            # session nonce: the receiver assembles per (object, push_id),
            # so interleaved pushes of the same object from two senders
            # (possibly with different chunk sizes) can never mix chunks
            push_id = f"{self.node_id[:8]}:{time.monotonic_ns()}"
            sem = self._push_peer_sems.setdefault(
                node_id, asyncio.Semaphore(cfg.push_max_chunks_in_flight)
            )

            failed = [False]
            landed = [False]  # receiver confirmed the object is in its store

            async def send(payload):
                try:
                    reply = await peer.request(
                        "push_chunks", payload, timeout=cfg.gcs_rpc_timeout_s
                    )
                    ok = bool(reply.get("ok") or reply.get("have"))
                    if reply.get("assembled") or reply.get("have"):
                        landed[0] = True
                except Exception:
                    ok = False
                finally:
                    sem.release()
                if not ok:
                    failed[0] = True
                return ok

            sends = []
            off = 0
            while True:
                if failed[0]:
                    break  # a chunk already failed: stop wasting bandwidth
                # zero-copy chunk: a PickleBuffer over the mmap'd store
                # view rides the v2 frame out-of-band (in-band, one copy,
                # on a v1 peer); the view is written before request()
                # resolves, so buf.release() below never races the send
                view = buf.data[off:off + chunk]
                payload = {
                    "object_id": oid.binary(), "offset": off,
                    "total": total, "data": pickle.PickleBuffer(view),
                    "push_id": push_id,
                }
                if off == 0:
                    payload["metadata"] = buf.metadata
                await sem.acquire()
                sends.append(
                    spawn(send(payload))
                )
                off += view.nbytes
                if off >= total:
                    break
            results = await asyncio.gather(*sends, return_exceptions=True)
            sent_all = off >= total and not failed[0]
            # success requires an explicit landing ack (assembled / have):
            # per-chunk acks alone can all succeed while the receiver's
            # session expired mid-push and the object never materialized
            ok = (sent_all and all(r is True for r in results)
                  and landed[0])
            if ok:
                from ray_tpu._private import memview

                # sender path: zero-copy views straight off the slab
                # ("arena") vs a legacy file mapping ("file")
                memview.record_flow(
                    "push", total, time.perf_counter() - t0,
                    "arena" if buf.seg_id is not None else "file",
                    oid.hex())
            return ok
        finally:
            buf.release()

    def _drop_push_rx(self, key, st: dict):
        """Retire one push-rx session: return its byte charge AND
        discard its partially-written slab reservation (tombstoned dead,
        uncharged) — an abandoned session must not leak an unsealed
        entry eroding arena capacity until restart."""
        self._push_rx.pop(key, None)
        res = st.get("res")
        if res is not None:
            try:
                res.abandon()
            except Exception:
                logger.exception("push-rx reservation abandon failed")
        self._pull_gate.uncharge(st["total"])

    def _expire_push_rx(self, now: float):
        """Drop abandoned assemblies (sender died mid-push) and return
        their byte charges to the transfer budget."""
        for k, st in list(self._push_rx.items()):
            if now - st["ts"] > cfg.push_rx_expiry_s:
                self._drop_push_rx(k, st)

    async def rpc_push_chunks(self, conn: Connection, p):
        """Receiver side: assemble out-of-order chunks of ONE push session
        (keyed by (object, push_id) so concurrent senders never interleave);
        finalize into the store and register the location when complete.
        Inbound bytes charge the same transfer budget as pulls — blocking
        here backpressures the sender through its chunk pipeline.

        Receive-side slab assembly: once the metadata-bearing chunk
        (offset 0) has arrived — the entry layout is [HDR][meta][data],
        so data offsets need the metadata length — the session reserves
        an unsealed slab entry and every chunk pwrites straight into the
        segment at its offset; the seal is the same atomic state-word
        flip a local put uses, performed only when all bytes arrived.
        Chunks that beat the metadata chunk stage in heap briefly and
        flush into the reservation when it exists."""
        oid = ObjectID(p["object_id"])
        if self.store.contains(oid):
            # drop any in-progress assembly of this object (e.g. a slower
            # concurrent push) and return its pull-gate byte charge now
            # rather than stranding it until the expiry sweep
            for k, st in list(self._push_rx.items()):
                if k[0] == oid.binary():
                    self._drop_push_rx(k, st)
            return {"have": True}
        now = time.monotonic()
        self._expire_push_rx(now)
        key = (oid.binary(), p.get("push_id", ""))
        st = self._push_rx.get(key)
        if st is None:
            await self._pull_gate.charge(p["total"])
            if self.store.contains(oid):  # landed while we waited
                self._pull_gate.uncharge(p["total"])
                return {"have": True}
            # charge() suspended: a sibling chunk of this session may have
            # created the state meanwhile — overwriting it would drop its
            # chunk and leak a second charge
            st = self._push_rx.get(key)
            if st is not None:
                self._pull_gate.uncharge(p["total"])
            else:
                st = self._push_rx[key] = {
                    "parts": {}, "meta": None, "total": p["total"],
                    "ts": now, "t0": now, "res": None, "heap": False,
                    "got": 0, "seen": set(),
                }
        st["ts"] = now
        if p.get("metadata") is not None:
            st["meta"] = p["metadata"]
        if st["res"] is None and not st["heap"] and st["meta"] is not None:
            reserve = getattr(self.store, "reserve", None)
            if reserve is not None:
                st["res"] = reserve(oid, st["meta"], st["total"])
            if st["res"] is None:
                st["heap"] = True  # fall back for the session's lifetime
            else:
                try:
                    for off, d in st["parts"].items():
                        st["res"].write(off, d)
                except (ValueError, OSError):
                    # same contract as the per-chunk guard below: a bad
                    # offset / ENOSPC must retire the session (tombstone
                    # + uncharge) instead of leaking it until expiry
                    self._drop_push_rx(key, st)
                    return {"ok": False}
                st["parts"] = {}
        if p["offset"] not in st["seen"]:
            st["seen"].add(p["offset"])
            st["got"] += len(p["data"])
            if st["res"] is not None:
                try:
                    st["res"].write(p["offset"], p["data"])
                except (ValueError, OSError):
                    self._drop_push_rx(key, st)
                    return {"ok": False}
            else:
                st["parts"][p["offset"]] = p["data"]
        if st["got"] >= st["total"]:
            self._push_rx.pop(key, None)
            if st["res"] is not None:
                path = "arena"
                ok = st["res"].seal()
                if not ok:
                    self._pull_gate.uncharge(st["total"])
                    # a racing session's seal winning the ledger is a
                    # successful landing from the sender's viewpoint
                    if self.store.contains(oid):
                        return {"have": True}
                    return {"ok": False}
            else:
                path = "heap"
                parts = [st["parts"][k] for k in sorted(st["parts"])]
                if not self.store.contains(oid):
                    self.store.put(oid, st["meta"], parts, st["total"])
            self._pull_gate.uncharge(st["total"])
            from ray_tpu._private import memview

            memview.record_flow("push_rx", st["total"],
                                now - st.get("t0", now), path,
                                oid.hex())
            # unblock local pull waiters and register the new copy
            fut = self._pulls_inflight.get(oid.binary())
            if fut is not None and not fut.done():
                fut.set_result(True)
            try:
                await self.gcs.request(
                    "add_object_location",
                    {"object_id": oid.binary(), "node_id": self.node_id},
                )
            except Exception:
                pass
            self._dispatch_event.set()
            return {"ok": True, "assembled": True}
        return {"ok": True}

    async def rpc_push_object(self, conn: Connection, p):
        """Driver-facing: push a (locally ensured) object to peers."""
        oid = ObjectID(p["object_id"])
        if not await self._ensure_local(oid.binary(), priority=PULL_PRIO_GET):
            return {"ok": False, "error": "object not obtainable locally"}
        results = await asyncio.gather(
            *[self.push_object(oid, n) for n in p["node_ids"]
              if n != self.node_id]
        )
        return {"ok": all(results), "pushed": sum(bool(r) for r in results)}

    async def rpc_broadcast_object(self, conn: Connection, p):
        """Binary-tree broadcast: push to the head of each half of the
        target list, then delegate the rest of that half to the head —
        log2 depth, every link pushes at full chunk pipeline (ray parity:
        the reference's 1GiB-to-N-nodes broadcast benchmark shape)."""
        oid = ObjectID(p["object_id"])
        entered = time.monotonic()
        # the caller's remaining time budget rides down the tree so deep
        # hops don't spuriously time out on big broadcasts
        budget = float(p.get("timeout") or cfg.object_pull_timeout_s * 4)
        if not await self._ensure_local(oid.binary(), priority=PULL_PRIO_GET):
            return {"ok": False, "error": "object not obtainable locally"}
        targets = [n for n in p["node_ids"] if n != self.node_id]
        if not targets:
            return {"ok": True, "nodes": 0}

        async def fan(half):
            try:
                if not half:
                    return True
                head, rest = half[0], half[1:]
                if not await self.push_object(oid, head):
                    # head unreachable: flat-push the rest from here instead
                    results = await asyncio.gather(
                        *[self.push_object(oid, n) for n in rest]
                    )
                    return all(results)
                if not rest:
                    return True
                peer = await self._peer(head)
                if peer is None:
                    return False
                remaining = max(1.0, budget - (time.monotonic() - entered))
                reply = await peer.request(
                    "broadcast_object",
                    {"object_id": oid.binary(), "node_ids": rest,
                     "timeout": remaining * 0.9},
                    timeout=remaining,
                )
                return bool(reply.get("ok"))
            except Exception as e:  # noqa: BLE001 — a failed half must not
                # cancel the sibling half's in-flight pushes
                logger.warning("broadcast subtree failed: %s", e)
                return False

        mid = (len(targets) + 1) // 2
        ok = await asyncio.gather(
            fan(targets[:mid]), fan(targets[mid:]), return_exceptions=True
        )
        return {"ok": all(r is True for r in ok), "nodes": len(targets)}

    async def rpc_fetch_object(self, conn: Connection, p):
        oid = ObjectID(p["object_id"])
        buf = self.store.get(oid)
        if buf is None:
            return {"exists": False}
        try:
            total = len(buf.data)
            off = p["offset"]
            # zero-copy chunk straight off the mmap; Finalized defers the
            # buffer release until the response frame reached the transport
            out = {
                "exists": True, "total": total,
                "data": pickle.PickleBuffer(buf.data[off: off + p["chunk"]]),
            }
            if off == 0:
                out["metadata"] = buf.metadata
        except BaseException:
            buf.release()  # failed before handing off: don't leak the mmap
            raise
        return Finalized(out, buf.release)

    def rpc_delete_object(self, conn: Connection, p):
        self.store.delete(ObjectID(p["object_id"]))

    def rpc_delete_objects(self, conn: Connection, p):
        """Batched GCS free broadcast (one frame per release burst)."""
        self._delete_local(p["object_ids"])

    def _delete_local(self, oids):
        many = getattr(self.store, "delete_many", None)
        if many is not None:
            many([ObjectID(oid) for oid in oids])
            return
        for oid in oids:
            self.store.delete(ObjectID(oid))

    async def rpc_owner_call(self, conn: Connection, p):
        """Route a request to an owning core worker anywhere in the cluster
        (generic transport for the borrower protocol: borrow_add,
        wait_ref_removed, release_return_pins, reconstruct_object —
        ray: core_worker.h WaitForRefRemoved / owner RPCs)."""
        node_id, client_id = tuple(p["owner"])
        timeout = p.get("timeout", cfg.gcs_rpc_timeout_s)
        if node_id == self.node_id:
            c = self.clients.get(client_id)
            if c is None or c.closed:
                return {"owner_dead": True}
            try:
                return await c.request(p["method"], p["payload"], timeout=timeout)
            except asyncio.TimeoutError:
                return {"timeout": True}
            except Exception:
                return {"owner_dead": True}
        peer = await self._peer(node_id)
        if peer is None:
            return {"owner_dead": True}
        try:
            return await peer.request("owner_call", p, timeout=timeout + 5.0)
        except asyncio.TimeoutError:
            return {"timeout": True}
        except Exception:
            return {"owner_dead": True}

    async def rpc_report_lost_object(self, conn: Connection, p):
        """Owner detected a lost plasma copy: drop the local record and the
        GCS location so pulls don't chase a dead file
        (ray: object_recovery_manager.h object-loss handling)."""
        oid = p["object_id"]
        # forget, not delete: a loss is not a free — reconstruction will
        # re-put this oid and must not hit a pending-delete tombstone
        forget = getattr(self.store, "forget", self.store.delete)
        forget(ObjectID(oid))
        try:
            await self.gcs.request(
                "remove_object_location",
                {"object_id": oid, "node_id": self.node_id},
            )
        except Exception:
            pass
        return {}

    async def rpc_fetch_owned_routed(self, conn: Connection, p):
        """Route a borrower's small-object fetch to the owning core worker
        (simplified owner-based object directory lookup)."""
        node_id, client_id = tuple(p["owner"])
        if node_id == self.node_id:
            c = self.clients.get(client_id)
            if c is None or c.closed:
                return {"unknown": True, "owner_dead": True}
            try:
                return await c.request(
                    "fetch_owned", {"object_id": p["object_id"]}, timeout=10.0
                )
            except Exception:
                return {"unknown": True}
        peer = await self._peer(node_id)
        if peer is None:
            return {"unknown": True, "owner_dead": True}
        try:
            return await peer.request(
                "fetch_owned_routed",
                {"owner": (node_id, client_id), "object_id": p["object_id"]},
                timeout=10.0,
            )
        except Exception:
            return {"unknown": True}

    async def rpc_free_object(self, conn: Connection, p):
        try:
            await self.gcs.request("free_object", {"object_id": p["object_id"]})
        except Exception:
            pass
        return {}

    async def rpc_free_objects(self, conn: Connection, p):
        """Tick-batched frees from an owner (one frame per release burst).
        The LOCAL copy is deleted synchronously — the owner only frees at
        cluster-wide refcount zero, so this is safe, and it returns the
        pages to the store's recycling pool NOW instead of after the GCS
        round-trip (a put/free loop would otherwise never see a warm
        pool). The GCS broadcast still clears remote copies."""
        try:
            self._delete_local(p["object_ids"])
        except Exception:
            pass
        try:
            await self.gcs.request(
                "free_objects", {"object_ids": list(p["object_ids"])}
            )
        except Exception:
            pass
        return {}

    # ------------------------------------------------------------------
    # profiling (ray: dashboard reporter's py-spy stack dumps — here the
    # workers self-report via sys._current_frames)
    # ------------------------------------------------------------------
    async def rpc_node_stacks(self, conn: Connection, p):
        """Stack dumps of every live worker on this node, gathered
        CONCURRENTLY — wedged workers are the very thing this exists to
        debug; waiting 10s for each in turn would blow the caller's
        budget and drop the healthy workers' stacks too."""
        live = [
            w for w in self.all_workers.values()
            if w.conn is not None and not w.conn.closed
        ]

        async def dump(w):
            try:
                return await w.conn.request(
                    "dump_stacks", {},
                    timeout=cfg.worker_dump_stacks_timeout_s,
                )
            except Exception:
                return {"pid": w.proc.pid, "error": "unreachable"}

        dumps = list(await asyncio.gather(*[dump(w) for w in live]))
        return {"node_id": self.node_id, "workers": dumps}

    # -- on-demand profiling fan-out (profiler.py) ---------------------
    def _profiler(self):
        svc = getattr(self, "_profiler_svc", None)
        if svc is None:
            from ray_tpu._private import profiler

            svc = self._profiler_svc = profiler.ProfilerService(
                role="raylet"
            )
        return svc

    async def rpc_profile_start(self, conn: Connection, p):
        return self._profiler().start(p or {})

    async def rpc_profile_stop(self, conn: Connection, p):
        out = self._profiler().stop(p or {})
        out["node_id"] = self.node_id
        return out

    async def rpc_profile_status(self, conn: Connection, p):
        return self._profiler().status()

    async def rpc_profile_node(self, conn: Connection, p):
        """Profile every live worker on this node (plus the raylet
        itself) for one window, CONCURRENTLY — each worker runs its own
        start/sample/stop session and the results come back as one list
        (the GCS merges node lists cluster-wide)."""
        p = dict(p or {})
        duration = min(float(p.get("duration") or 5.0),
                       cfg.profiler_max_duration_s)
        p["duration"] = duration
        actor_filter = p.get("actor_id")
        if isinstance(actor_filter, str):
            try:
                actor_filter = bytes.fromhex(actor_filter)
            except ValueError:
                pass
        live = [
            w for w in self.all_workers.values()
            if w.conn is not None and not w.conn.closed
        ]
        if actor_filter:
            live = [w for w in live if w.actor_id == actor_filter]

        async def one(w: _Worker):
            try:
                out = await w.conn.request(
                    "profile_run", p, timeout=duration + 30.0
                )
            except Exception as e:
                return {"pid": w.proc.pid, "node_id": self.node_id,
                        "error": f"{type(e).__name__}: {e}"}
            out.setdefault("node_id", self.node_id)
            return out

        jobs = [one(w) for w in live]
        include_self = bool(p.get("include_raylet", True)) \
            and not actor_filter
        if include_self:
            async def self_prof():
                out = await self._profiler().run(p)
                out["node_id"] = self.node_id
                return out

            jobs.append(self_prof())
        processes = list(await asyncio.gather(*jobs))
        return {"node_id": self.node_id, "processes": processes}

    # -- metrics plane (metrics_core.py) -------------------------------
    async def rpc_metrics_snapshot(self, conn: Connection, p):
        from ray_tpu._private import metrics_core

        return metrics_core.process_snapshot(
            "raylet", {"node_id": self.node_id})

    async def rpc_metrics_node(self, conn: Connection, p):
        """This raylet's snapshot plus every live worker's, gathered
        CONCURRENTLY (one wedged worker must not stall the node scrape —
        same posture as profile_node)."""
        from ray_tpu._private import metrics_core

        live = [
            w for w in self.all_workers.values()
            if w.conn is not None and not w.conn.closed
        ]

        async def one(w: _Worker):
            try:
                out = await w.conn.request(
                    "metrics_snapshot", {},
                    timeout=cfg.metrics_scrape_timeout_s)
            except Exception as e:
                return {"pid": w.proc.pid, "node_id": self.node_id,
                        "error": f"{type(e).__name__}: {e}"}
            out.setdefault("node_id", self.node_id)
            return out

        processes = list(await asyncio.gather(*[one(w) for w in live]))
        processes.append(metrics_core.process_snapshot(
            "raylet", {"node_id": self.node_id}))
        return {"node_id": self.node_id, "processes": processes}

    # -- step observatory (steptrace.py) -------------------------------
    async def rpc_steptrace_node(self, conn: Connection, p):
        """Every live worker's steptrace ring, gathered CONCURRENTLY
        (same posture as metrics_node: one wedged worker must not stall
        the scrape). The raylet itself runs no collectives or train
        steps, so it contributes no snapshot of its own."""
        live = [
            w for w in self.all_workers.values()
            if w.conn is not None and not w.conn.closed
        ]

        async def one(w: _Worker):
            try:
                out = await w.conn.request(
                    "steptrace_snapshot", {},
                    timeout=cfg.steptrace_scrape_timeout_s)
            except Exception as e:
                return {"pid": w.proc.pid, "node_id": self.node_id,
                        "error": f"{type(e).__name__}: {e}"}
            out.setdefault("node_id", self.node_id)
            return out

        processes = list(await asyncio.gather(*[one(w) for w in live]))
        return {"node_id": self.node_id, "processes": processes}

    # -- request observatory (reqtrace.py) -----------------------------
    async def rpc_reqtrace_node(self, conn: Connection, p):
        """Every live worker's reqtrace ring, gathered CONCURRENTLY
        (same posture as steptrace_node: one wedged worker must not
        stall the scrape). Serve proxies and replicas are actors in
        worker processes, so the node fan-out covers them; the raylet
        itself serves no requests and contributes no snapshot."""
        live = [
            w for w in self.all_workers.values()
            if w.conn is not None and not w.conn.closed
        ]

        async def one(w: _Worker):
            try:
                out = await w.conn.request(
                    "reqtrace_snapshot", {},
                    timeout=cfg.reqtrace_scrape_timeout_s)
            except Exception as e:
                return {"pid": w.proc.pid, "node_id": self.node_id,
                        "error": f"{type(e).__name__}: {e}"}
            out.setdefault("node_id", self.node_id)
            return out

        processes = list(await asyncio.gather(*[one(w) for w in live]))
        return {"node_id": self.node_id, "processes": processes}

    # -- memory observatory (memview.py) -------------------------------
    async def rpc_memview_node(self, conn: Connection, p):
        """This node's object-plane view: every live worker's memview
        snapshot (owned tables + reference sets + flow rings), gathered
        CONCURRENTLY, plus the raylet's own snapshot carrying the store
        ledger — per-object lifecycle rows and the arena introspection
        (segment occupancy, dead byte ranges, recycling pool, per-client
        charge, overshoot attribution)."""
        from ray_tpu._private import memview

        live = [
            w for w in self.all_workers.values()
            if w.conn is not None and not w.conn.closed
        ]

        async def one(w: _Worker):
            try:
                out = await w.conn.request(
                    "memview_snapshot", {},
                    timeout=cfg.memview_scrape_timeout_s)
            except Exception as e:
                return {"pid": w.proc.pid, "node_id": self.node_id,
                        "error": f"{type(e).__name__}: {e}"}
            out.setdefault("node_id", self.node_id)
            return out

        limit = (p or {}).get("limit") or 10_000

        def collect():
            # store introspection is lock-held python over up to `limit`
            # ledger rows plus flock probes of the recycling pool: run
            # it on an executor thread so a full store never stalls the
            # raylet event loop (heartbeats, dispatch, pushes).
            # getattr-guarded: the native C++ store (slab_arena=0) has
            # no introspection surface yet — the node still reports its
            # workers.
            own = memview.process_snapshot({"node_id": self.node_id,
                                            "role": "raylet"})
            intro = getattr(self.store, "arena_introspect", None)
            objs = getattr(self.store, "memview_objects", None)
            own["store"] = {
                "arena": intro() if intro is not None else None,
                "objects": objs(limit) if objs is not None else [],
            }
            return own

        workers, own = await asyncio.gather(
            asyncio.gather(*[one(w) for w in live]),
            asyncio.get_running_loop().run_in_executor(None, collect),
        )
        processes = list(workers) + [own]
        return {"node_id": self.node_id, "processes": processes}

    # ------------------------------------------------------------------
    # placement groups (bundle resources; 2-phase)
    # ------------------------------------------------------------------
    async def rpc_pg_prepare(self, conn: Connection, p):
        from ray_tpu._private.common import rewrite_resources_for_pg

        # App-level idempotency: a duplicated/retried prepare for a bundle
        # we already hold must ack without reserving twice. (An rpc-level
        # idem token is wrong here: pg_cancel legitimately rolls the
        # reservation back between placement attempts, and a cached "ok"
        # would ack a later attempt without actually re-reserving.)
        if (p["pg_id"], p["bundle_index"]) in self.pg_bundles:
            return {"ok": True}
        resources = p["resources"]
        if not res_fits(resources, self.resources_available):
            return {"ok": False}
        res_sub(self.resources_available, resources)
        named = rewrite_resources_for_pg(resources, p["pg_id"], p["bundle_index"])
        self.pg_bundles[(p["pg_id"], p["bundle_index"])] = {
            "original": resources, "named": named, "committed": False,
        }
        res_add(self.resources_total, named)
        res_add(self.resources_available, named)
        self._dispatch_event.set()
        return {"ok": True}

    async def rpc_pg_commit(self, conn: Connection, p):
        b = self.pg_bundles.get((p["pg_id"], p["bundle_index"]))
        if b:
            b["committed"] = True
        return {"ok": True}

    def rpc_pg_cancel(self, conn: Connection, p):
        self._return_bundle(p["pg_id"], p["bundle_index"])

    def rpc_pg_return_if_idle(self, conn: Connection, p):
        """Repack-pass release: return the bundle ONLY if nothing uses or
        is about to use it — the GCS plans migrations from its heartbeat
        view, which can be a beat stale, so this raylet (the authority on
        its own consumption) gates the actual release. Atomic within the
        handler: the check and the return happen in one event-loop step."""
        key = (p["pg_id"], p["bundle_index"])
        b = self.pg_bundles.get(key)
        if not b:
            return {"ok": False, "reason": "unknown bundle"}
        # consumed capacity: any named resource below its full reservation
        for k, v in b["named"].items():
            if self.resources_available.get(k, 0.0) < v - 1e-9:
                return {"ok": False, "reason": "in use"}
        # demand racing in: a queued/running task naming this pg's
        # formatted resources would dispatch into the hole the migration
        # leaves behind
        named = set(b["named"])
        for qt in list(self.ready) + list(self.waiting.values()) \
                + list(self.running.values()) \
                + list(self.infeasible.values()):
            if named & set(qt.resources):
                return {"ok": False, "reason": "queued demand"}
        self._return_bundle(*key)
        return {"ok": True}

    def rpc_pg_return(self, conn: Connection, p):
        self._return_bundle(p["pg_id"], p["bundle_index"])

    def _return_bundle(self, pg_id: str, bundle_index: int):
        b = self.pg_bundles.pop((pg_id, bundle_index), None)
        if not b:
            return
        for k, v in b["named"].items():
            self.resources_total[k] = max(0.0, self.resources_total.get(k, 0.0) - v)
            self.resources_available[k] = max(
                0.0, self.resources_available.get(k, 0.0) - v
            )
            if self.resources_total.get(k, 0.0) <= 0:
                self.resources_total.pop(k, None)
                self.resources_available.pop(k, None)
        res_add(self.resources_available, b["original"])
        self._dispatch_event.set()

    # ------------------------------------------------------------------
    # misc / introspection
    # ------------------------------------------------------------------
    async def rpc_node_stats(self, conn: Connection, _):
        return {
            "node_id": self.node_id,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "num_workers": len(self.all_workers),
            "num_idle_workers": sum(len(q) for q in self.idle_workers.values()),
            "queued": len(self.ready) + len(self.waiting),
            "infeasible": len(self.infeasible),
            "infeasible_shapes": [dict(qt.resources)
                                  for qt in self.infeasible.values()][:5],
            "cluster_view_totals": {
                nid[:8]: dict(n.resources_total)
                for nid, n in self.cluster_view.items()
            },
            "running": len(self.running),
            "store_used_bytes": self.store.used_bytes(),
            "counters": dict(self.counters),
        }

    async def rpc_cancel_task(self, conn: Connection, p):
        tid = p["task_id"]
        qt = self.waiting.pop(tid, None)
        if qt is None:
            qt = self.infeasible.pop(tid, None)
        if qt is None:
            qt = self.ready.remove_task(tid)
        if qt is not None:
            await self._route_to_owner(
                qt.spec.owner, "task_result",
                {"task_id": tid, "results": None, "error": "task cancelled",
                 "cancelled": True, "retriable": False, "attempt": qt.spec.attempt},
            )
            # release the spiller's resubmission liability, or a later
            # node death would resurrect the cancelled task
            await self._notify_spill_origin(qt.spec)
            return {"cancelled": True}
        running = self.running.get(tid)
        if running is not None and p.get("force") and running.worker is not None:
            running.worker.proc.terminate()
            return {"cancelled": True}
        return {"cancelled": False}
