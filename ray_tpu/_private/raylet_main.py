"""Raylet process entrypoint (analog of ray: src/ray/raylet/main.cc:109)."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os


async def amain(args):
    from ray_tpu._private.rpcio import enable_eager_tasks

    enable_eager_tasks(asyncio.get_running_loop())
    from ray_tpu._private.raylet import Raylet
    from ray_tpu._private.resource_spec import detect_resources

    resources, labels = detect_resources()
    if args.resources:
        resources.update(json.loads(args.resources))
    if args.labels:
        labels.update(json.loads(args.labels))
    raylet = Raylet(
        gcs_host=args.gcs_host,
        gcs_port=args.gcs_port,
        session_dir=args.session_dir,
        resources=resources,
        labels=labels,
        port=args.port,
    )
    port = await raylet.start()

    import signal

    async def _shutdown():
        try:
            await raylet.stop()
        finally:
            os._exit(0)

    loop = asyncio.get_running_loop()
    loop.add_signal_handler(signal.SIGTERM, lambda: asyncio.ensure_future(_shutdown()))
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{port}\n{raylet.node_id}")
        os.rename(tmp, args.port_file)
    await asyncio.Event().wait()


def main():
    from ray_tpu._private.profiling import maybe_profile

    maybe_profile("raylet")
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", default="127.0.0.1")
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--port-file", default=None)
    parser.add_argument("--resources", default=None, help="JSON resource overrides")
    parser.add_argument("--labels", default=None, help="JSON label overrides")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[raylet] %(levelname)s %(name)s: %(message)s",
    )
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
