"""URI-pluggable external storage for object spilling.

Reference parity: ray python/ray/_private/external_storage.py — the
reference's object_spilling_config selects a storage backend
(filesystem, S3 via smart_open) that IO workers stream spilled objects
through (src/ray/raylet/local_object_manager.h:40); restore brings them
back by URI. Here the raylet's store calls the same spill/restore/delete
contract; ``file://`` (or a bare path) is the filesystem backend, s3://
is boto3-gated, and tests register custom schemes to play the role of a
remote object store without network egress.

Spill keys are deterministic (object id derived), so a restarted raylet
can find a predecessor's spilled objects at the same URI — local-disk
spill dies with the node; external spill survives it.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional
from urllib.parse import urlparse


class ExternalStorage:
    """Contract: keys are opaque strings chosen by the caller; values are
    whole object files (the sealed on-disk format)."""

    def spill(self, key: str, local_path: str) -> None:
        """Upload local_path under key (overwrite allowed: objects are
        immutable, double-spill writes identical bytes)."""
        raise NotImplementedError

    def restore(self, key: str, local_path: str) -> bool:
        """Download key to local_path (atomically); False if absent."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """file:///mount/point — shared filesystem (NFS/GCS-fuse) or plain
    local dir (the classic spill-to-disk)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key)

    def spill(self, key: str, local_path: str) -> None:
        dst = self._path(key)
        tmp = dst + ".tmp"
        with open(local_path, "rb") as fi, open(tmp, "wb") as fo:
            while True:
                chunk = fi.read(8 * 1024 * 1024)
                if not chunk:
                    break
                fo.write(chunk)
        os.replace(tmp, dst)

    def spill_move(self, key: str, local_path: str) -> bool:
        """Adopt ``local_path`` as the spilled copy by rename — atomic
        and copy-free when the caller staged on this filesystem. False
        (e.g. EXDEV across devices) means fall back to ``spill``."""
        try:
            os.replace(local_path, self._path(key))
            return True
        except OSError:
            return False

    def restore(self, key: str, local_path: str) -> bool:
        src = self._path(key)
        if not os.path.exists(src):
            return False
        tmp = local_path + ".restoring"
        with open(src, "rb") as fi, open(tmp, "wb") as fo:
            while True:
                chunk = fi.read(8 * 1024 * 1024)
                if not chunk:
                    break
                fo.write(chunk)
        os.replace(tmp, local_path)
        return True

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))


class S3Storage(ExternalStorage):
    """s3://bucket/prefix — boto3-gated (absent in this image: a clear
    error at construction, mirroring the reference's smart_open
    dependency for S3 spilling)."""

    def __init__(self, bucket: str, prefix: str):
        try:
            import boto3
        except ImportError as e:
            raise RuntimeError(
                "s3:// spilling needs boto3, which is not installed; use "
                "file:// or register a custom scheme via "
                "register_external_storage_scheme"
            ) from e
        self._s3 = boto3.client("s3")
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def spill(self, key: str, local_path: str) -> None:
        self._s3.upload_file(local_path, self.bucket, self._key(key))

    def restore(self, key: str, local_path: str) -> bool:
        import botocore.exceptions

        tmp = local_path + ".restoring"
        try:
            self._s3.download_file(self.bucket, self._key(key), tmp)
        except botocore.exceptions.ClientError:
            return False
        os.replace(tmp, local_path)
        return True

    def delete(self, key: str) -> None:
        self._s3.delete_object(Bucket=self.bucket, Key=self._key(key))

    def exists(self, key: str) -> bool:
        import botocore.exceptions

        try:
            self._s3.head_object(Bucket=self.bucket, Key=self._key(key))
            return True
        except botocore.exceptions.ClientError:
            return False


_SCHEMES: Dict[str, Callable[[str], ExternalStorage]] = {}


def register_external_storage_scheme(
    scheme: str, factory: Callable[[str], ExternalStorage]
) -> None:
    """Plug a custom backend: ``factory(uri) -> ExternalStorage``. Tests
    use this as the s3-style remote stand-in; deployments can wire GCS,
    Azure, or an internal blob service the same way."""
    _SCHEMES[scheme] = factory


def make_external_storage(uri: Optional[str]) -> Optional[ExternalStorage]:
    """None for empty; FileSystemStorage for bare paths and file://;
    scheme registry / S3 otherwise."""
    if not uri:
        return None
    parsed = urlparse(uri)
    if parsed.scheme in ("", "file"):
        return FileSystemStorage(parsed.path or uri)
    if parsed.scheme in _SCHEMES:
        return _SCHEMES[parsed.scheme](uri)
    if parsed.scheme == "s3":
        return S3Storage(parsed.netloc, parsed.path)
    raise ValueError(
        f"unknown external storage scheme {parsed.scheme!r} in {uri!r}; "
        f"known: file, s3" + (", " + ", ".join(_SCHEMES) if _SCHEMES else "")
    )


def is_local_spill_uri(uri: Optional[str]) -> bool:
    """True when the target is plain-filesystem (native-store fast path
    applies); non-file schemes route through the Python store + driver."""
    if not uri:
        return True
    return urlparse(uri).scheme in ("", "file")
