"""Request observatory: per-request serve tracing + phase attribution.

The six planes so far (chaos/profiling/metrics/logs/steptrace/memview)
watch the control plane, the training loop, and the object plane; this
one lights up the SERVE data plane — answering "where did a slow request
spend its time" (proxy? routing? replica queue? batch window? execute?
serialize? stream?) with per-deployment per-replica attribution. Every
process keeps ONE fixed-size ring of small tuples recording

- **phase spans**: the proxy mints a request id per HTTP/handle call and
  threads it through the handle→replica RPC envelope; every hop records
  its phase against that id — ``ingress`` (proxy receive + route match),
  ``route`` (chosen replica + the router's inflight snapshot at decision
  time), ``queue`` (handle send → user code start, the replica-side
  wait), ``batch_wait`` (submit → flush inside ``serve.batch``, with
  batch key + size), ``execute`` (user code), ``serialize`` (proxy
  response construction);
- **marks**: streaming ``first_byte`` / ``last_byte`` timestamps, so
  TTFT is a first-class number instead of a log grep.

Metrics-core discipline applies (see metrics_core.py): ``record_*`` is
one module-global flag load + a tuple pack + a list store — no locks
(GIL-atomic enough for telemetry; a torn write loses one record, never
corrupts structure) — and the whole plane is flag-gated
(``RAY_TPU_REQTRACE_ENABLED=0`` / cfg ``reqtrace_enabled``) so it costs
nothing when off. The bench lane (BENCH_REQTRACE_OVERHEAD=1) gates the
calibrated per-request record cost <2% of a proxy round trip and
asserts zero ring records when disabled.

Timestamps are ``time.time()`` (wall): queue-wait spans START on the
caller's clock (the handle stamps the send time into the RPC envelope)
and END on the replica's, so the clocks must share an epoch — the same
tradeoff steptrace makes for cross-rank skew. Within one host that is
exact; across hosts the queue reading carries NTP error.

The GCS folds per-process records into rolling metrics via
``RequestAggregator``: ``serve_request_phase_seconds{app,deployment,
phase}`` and ``serve_request_ttft_seconds{app,deployment}`` histograms
riding the existing /metrics cluster scrape (p50/p95/p99 come free from
the metrics core) — exactly the signals the admission-control and
autoscaling ROADMAP levers will consume. ``merge_processes`` joins
proxy+replica records by request id into per-request phase breakdowns,
per-deployment summaries, per-replica phase profiles, and **skew
verdicts** ("replica r3 is slow, and it's queue wait, not execute");
``chrome_trace`` renders the merged view as Perfetto JSON, one track
per replica, for ``ray_tpu serve timeline`` /
``util.state.request_timeline()`` / the dashboard Serve tab.
"""

from __future__ import annotations

import contextvars
import os
import time
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "set_enabled", "is_enabled", "record_calls", "reset",
    "new_request_id", "record_span", "record_mark", "CURRENT",
    "snapshot", "process_snapshot",
    "merge_requests", "merge_processes", "deployment_summary",
    "replica_breakdown", "skew_verdicts", "chrome_trace",
    "RequestAggregator",
]

_enabled = os.environ.get("RAY_TPU_REQTRACE_ENABLED", "1").lower() not in (
    "0", "false", "no")
_explicit = False  # set_enabled() was called: runtime override wins
# instrumentation event count (the bench lane's calibrated-cost x count
# estimator multiplies this, same discipline as steptrace._events)
_events = 0

_RING_DEFAULT = 8192
_ring: List[Any] = []
_ring_size = 0
_idx = 0  # monotonic per-process write index (ring slot = _idx % size)
# process identity for the aggregator's exactly-once fold: a recycled
# pid whose new ring already wrote PAST the dead process's high-water
# mark is undetectable from idx alone — the epoch disambiguates
_EPOCH = os.urandom(4).hex()

# per-request identity for code that runs UNDER a request but doesn't see
# the RPC envelope (serve.batch flushes, nested helpers): the replica sets
# (rid, app, deployment, replica) around user-code invocation. Contextvars
# propagate through asyncio awaits, which is exactly the scope needed.
CURRENT: "contextvars.ContextVar[Optional[tuple]]" = contextvars.ContextVar(
    "reqtrace_current", default=None)


def _fold_cfg():
    """Fold cfg ``reqtrace_enabled`` (itself env-overridable as
    ``RAY_TPU_reqtrace_enabled``) into the flag — the documented kill
    switch must gate the record paths, not just the surfaces. An
    explicit set_enabled() always wins."""
    global _enabled
    if _explicit:
        return
    try:
        from ray_tpu._private.config import GLOBAL_CONFIG

        if not GLOBAL_CONFIG.reqtrace_enabled:
            _enabled = False
    except Exception:
        pass


_fold_cfg()


def set_enabled(flag: bool):
    global _enabled, _explicit
    _explicit = True
    _enabled = bool(flag)


def is_enabled() -> bool:
    _fold_cfg()
    return _enabled


def record_calls() -> int:
    """Total record_* calls in this process since import (the overhead
    lane's event count)."""
    return _events


def reset():
    """Drop all records and counters (tests / bench phases)."""
    global _ring, _ring_size, _idx, _events
    _ring = []
    _ring_size = 0
    _idx = 0
    _events = 0


def new_request_id() -> str:
    """Mint a request id (16 hex chars): the proxy mints one per HTTP
    call, the handle mints one per direct ``.remote()`` that arrived
    without one — every hop's records join on it."""
    return os.urandom(8).hex()


# ---------------------------------------------------------------------------
# record paths (hot: flag load + tuple pack + list store)
# ---------------------------------------------------------------------------

def _ensure_ring():
    global _ring, _ring_size
    if _ring_size == 0:
        _fold_cfg()  # late system_config overrides land before any write
        size = _RING_DEFAULT
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG

            size = int(GLOBAL_CONFIG.reqtrace_ring_size)
        except Exception:
            pass
        _ring = [None] * max(16, size)
        _ring_size = len(_ring)
    return _ring


def _ring_slot():
    ring = _ring
    if not ring:
        ring = _ensure_ring()
        if not _enabled:
            return None
    return ring


def record_span(rid: str, phase: str, start: float, end: float,
                app: str = "", deployment: str = "", replica: str = "",
                detail: Optional[dict] = None):
    global _events, _idx
    if not _enabled or not rid:
        return
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    ring[_idx % _ring_size] = ("span", _idx, rid, phase, app, deployment,
                               replica, start, end, detail)
    _idx += 1


def record_mark(rid: str, name: str, ts: float, app: str = "",
                deployment: str = "", replica: str = ""):
    global _events, _idx
    if not _enabled or not rid:
        return
    ring = _ring_slot()
    if ring is None:
        return
    _events += 1
    ring[_idx % _ring_size] = ("mark", _idx, rid, name, app, deployment,
                               replica, ts)
    _idx += 1


# ---------------------------------------------------------------------------
# snapshot (the reqtrace_snapshot RPC payload)
# ---------------------------------------------------------------------------

def snapshot() -> List[dict]:
    """The ring contents as dicts, oldest first. ``idx`` is the
    process-monotonic record index — consumers (RequestAggregator) use
    it to fold each record exactly once across repeated scrapes."""
    if _idx == 0:
        return []
    ring, size, idx = _ring, _ring_size, _idx
    if idx <= size:
        raw = ring[:idx]
    else:
        cut = idx % size
        raw = ring[cut:] + ring[:cut]
    out = []
    for rec in raw:
        if rec is None:  # torn slot mid-wrap: skip, never corrupt
            continue
        if rec[0] == "span":
            out.append({"kind": "span", "idx": rec[1], "rid": rec[2],
                        "phase": rec[3], "app": rec[4],
                        "deployment": rec[5], "replica": rec[6],
                        "start": rec[7], "end": rec[8],
                        "detail": rec[9]})
        elif rec[0] == "mark":
            out.append({"kind": "mark", "idx": rec[1], "rid": rec[2],
                        "name": rec[3], "app": rec[4],
                        "deployment": rec[5], "replica": rec[6],
                        "ts": rec[7]})
    return out


def process_snapshot(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The ``reqtrace_snapshot`` RPC payload: ring dump + identity +
    drop accounting."""
    out: Dict[str, Any] = {
        "pid": os.getpid(),
        "epoch": _EPOCH,
        "records": snapshot(),
        "dropped": max(0, _idx - _ring_size) if _ring_size else 0,
        "record_calls": _events,
    }
    if extra:
        out.update(extra)
    return out


# ---------------------------------------------------------------------------
# merge (GCS-side; pure functions, unit-testable)
# ---------------------------------------------------------------------------

def _pct(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def merge_requests(records: Sequence[dict]) -> List[dict]:
    """Join per-process span/mark records by request id into one row per
    request, ordered by start time.

    Each row: ``{rid, app, deployment, replica, start, end, total,
    phases: [{phase, start, end, dur, replica, detail}], marks:
    {name: ts}, ttft, missing}`` — ``replica`` is the one the replica-
    side spans ran on (falling back to the route decision), ``ttft`` is
    first_byte − request start when a first_byte mark exists, and
    ``missing`` is "replica" when the route span names a replica but no
    replica-side span ever arrived (replica died, ring overwrote, scrape
    raced — the row is still rendered from the proxy's half)."""
    by_rid: Dict[str, dict] = {}
    for rec in records:
        rid = rec.get("rid")
        if not rid:
            continue
        row = by_rid.get(rid)
        if row is None:
            row = by_rid[rid] = {"rid": rid, "app": "", "deployment": "",
                                 "replica": "", "phases": [], "marks": {}}
        if rec.get("kind") == "span":
            row["phases"].append({
                "phase": rec["phase"], "start": rec["start"],
                "end": rec["end"],
                "dur": max(0.0, rec["end"] - rec["start"]),
                "replica": rec.get("replica") or "",
                "detail": rec.get("detail"),
            })
        elif rec.get("kind") == "mark":
            row["marks"][rec["name"]] = rec["ts"]
        for key in ("app", "deployment"):
            if not row[key] and rec.get(key):
                row[key] = rec[key]
    out = []
    _REPLICA_SIDE = ("queue", "execute", "batch_wait")
    for row in by_rid.values():
        if not row["phases"] and not row["marks"]:
            continue
        # dedup retried/re-scraped identical spans (same phase+start)
        seen = set()
        phases = []
        for ph in sorted(row["phases"], key=lambda p: p["start"]):
            key = (ph["phase"], ph["replica"], round(ph["start"], 6))
            if key in seen:
                continue
            seen.add(key)
            phases.append(ph)
        row["phases"] = phases
        starts = [p["start"] for p in phases]
        ends = [p["end"] for p in phases]
        row["start"] = min(starts) if starts else min(
            row["marks"].values())
        row["end"] = max(ends + list(row["marks"].values())) \
            if (ends or row["marks"]) else row["start"]
        row["total"] = row["end"] - row["start"]
        # the replica that served it: replica-side spans first, else the
        # route decision's choice
        replica = next((p["replica"] for p in phases
                        if p["phase"] in _REPLICA_SIDE and p["replica"]),
                       "")
        routed = next((p for p in phases if p["phase"] == "route"), None)
        if not replica and routed:
            replica = (routed.get("detail") or {}).get("replica", "") \
                or routed.get("replica", "")
        row["replica"] = replica
        fb = row["marks"].get("first_byte")
        row["ttft"] = (fb - row["start"]) if fb is not None else None
        has_replica_side = any(p["phase"] in _REPLICA_SIDE for p in phases)
        row["missing"] = "replica" if (routed and not has_replica_side) \
            else None
        out.append(row)
    out.sort(key=lambda r: r["start"])
    return out


def _phase_totals(rows: Sequence[dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for row in rows:
        for ph in row["phases"]:
            out[ph["phase"]] = out.get(ph["phase"], 0.0) + ph["dur"]
    return out


def deployment_summary(rows: Sequence[dict]) -> List[dict]:
    """Per-(app, deployment) latency summary: request count, total
    p50/p95/p99, TTFT p50/p95/p99 (streaming requests only), and mean
    seconds per phase — the table ``ray_tpu serve requests`` prints."""
    groups: Dict[tuple, List[dict]] = {}
    for row in rows:
        groups.setdefault((row["app"], row["deployment"]), []).append(row)
    out = []
    for (app, deployment), rs in groups.items():
        totals = sorted(r["total"] for r in rs)
        ttfts = sorted(r["ttft"] for r in rs if r["ttft"] is not None)
        phase_tot = _phase_totals(rs)
        out.append({
            "app": app, "deployment": deployment, "count": len(rs),
            "p50": _pct(totals, 0.50), "p95": _pct(totals, 0.95),
            "p99": _pct(totals, 0.99),
            "ttft_p50": _pct(ttfts, 0.50) if ttfts else None,
            "ttft_p95": _pct(ttfts, 0.95) if ttfts else None,
            "ttft_p99": _pct(ttfts, 0.99) if ttfts else None,
            "phase_mean": {ph: tot / len(rs)
                           for ph, tot in sorted(phase_tot.items())},
            "missing_replica_side": sum(1 for r in rs if r["missing"]),
        })
    out.sort(key=lambda e: (e["app"], e["deployment"]))
    return out


def replica_breakdown(rows: Sequence[dict]) -> List[dict]:
    """Per-(app, deployment, replica) phase profile: request count and
    mean seconds per phase — the input to ``skew_verdicts``."""
    groups: Dict[tuple, List[dict]] = {}
    for row in rows:
        if not row["replica"]:
            continue
        groups.setdefault(
            (row["app"], row["deployment"], row["replica"]), []
        ).append(row)
    out = []
    for (app, deployment, replica), rs in groups.items():
        phase_tot = _phase_totals(rs)
        totals = sorted(r["total"] for r in rs)
        out.append({
            "app": app, "deployment": deployment, "replica": replica,
            "count": len(rs),
            "mean_total": sum(totals) / len(totals),
            "p95": _pct(totals, 0.95),
            "phase_mean": {ph: tot / len(rs)
                           for ph, tot in sorted(phase_tot.items())},
        })
    out.sort(key=lambda e: (e["app"], e["deployment"], e["replica"]))
    return out


def skew_verdicts(breakdown: Sequence[dict], min_requests: int = 5,
                  factor: float = 1.5) -> List[dict]:
    """Replica skew attribution: for every deployment with >=2 replicas
    that each served >= ``min_requests``, compare each replica's mean
    total latency against the MEDIAN of its peers; a replica beyond
    ``factor``x earns a verdict naming the phase that contributes the
    largest share of the excess — "replica r3 is slow, and it's queue
    wait, not execute"."""
    groups: Dict[tuple, List[dict]] = {}
    for entry in breakdown:
        if entry["count"] >= min_requests:
            groups.setdefault((entry["app"], entry["deployment"]),
                              []).append(entry)
    verdicts = []
    for (app, deployment), entries in groups.items():
        if len(entries) < 2:
            continue
        for entry in entries:
            peers = [e for e in entries if e is not entry]
            peer_totals = sorted(e["mean_total"] for e in peers)
            peer_median = peer_totals[len(peer_totals) // 2]
            if peer_median <= 0 or \
                    entry["mean_total"] < factor * peer_median:
                continue
            # which phase explains the excess: largest mean delta vs the
            # peers' mean for that phase
            deltas = {}
            for ph, mean in entry["phase_mean"].items():
                peer_mean = sum(e["phase_mean"].get(ph, 0.0)
                                for e in peers) / len(peers)
                deltas[ph] = mean - peer_mean
            dominant = max(deltas, key=deltas.get) if deltas else "?"
            verdicts.append({
                "kind": "slow_replica",
                "app": app, "deployment": deployment,
                "replica": entry["replica"],
                "mean_total": entry["mean_total"],
                "peer_median": peer_median,
                "ratio": entry["mean_total"] / peer_median,
                "dominant_phase": dominant,
                "phase_delta": round(deltas.get(dominant, 0.0), 6),
                "detail": (
                    f"replica {entry['replica']} mean "
                    f"{entry['mean_total'] * 1e3:.1f}ms vs peer median "
                    f"{peer_median * 1e3:.1f}ms "
                    f"({entry['mean_total'] / peer_median:.1f}x) — "
                    f"dominated by {dominant} "
                    f"(+{deltas.get(dominant, 0.0) * 1e3:.1f}ms/req)"),
            })
    verdicts.sort(key=lambda v: -v["ratio"])
    return verdicts


def merge_records(records: Sequence[dict]) -> Dict[str, Any]:
    """Fold a flat record stream into the merged serve view: per-request
    rows joined by rid, per-deployment summaries, per-replica phase
    profiles, and slow-replica skew verdicts."""
    rows = merge_requests(records)
    breakdown = replica_breakdown(rows)
    return {
        "requests": rows,
        "deployments": deployment_summary(rows),
        "replicas": breakdown,
        "verdicts": skew_verdicts(breakdown),
    }


def merge_processes(processes: Sequence[dict]) -> Dict[str, Any]:
    """Fold per-process reqtrace snapshots into one merged view."""
    flat: List[dict] = []
    for proc in processes:
        if proc.get("error"):
            continue
        flat.extend(proc.get("records", ()))
    return merge_records(flat)


def chrome_trace(merged: Dict[str, Any]) -> List[dict]:
    """Render a merged view as Chrome-trace JSON — loadable in Perfetto /
    chrome://tracing. One process row per replica (plus one for the
    proxy-side phases), phase slices on per-phase tracks, each slice
    stamped with its request id so a slow request reads end to end."""
    trace: List[dict] = []
    pids: Dict[str, int] = {}
    _PROXY_SIDE = ("ingress", "route", "serialize")

    def pid_of(name: str) -> int:
        pid = pids.get(name)
        if pid is None:
            pid = pids[name] = len(pids)
            trace.append({"name": "process_name", "ph": "M", "pid": pid,
                          "args": {"name": name}})
        return pid

    for row in merged.get("requests", ()):
        dep = f"{row['app']}/{row['deployment']}".strip("/") or "serve"
        for ph in row["phases"]:
            if ph["phase"] in _PROXY_SIDE:
                track = f"proxy ({dep})"
            else:
                track = f"replica {ph['replica'] or row['replica'] or '?'}"
            args = {"rid": row["rid"], "deployment": dep}
            if ph.get("detail"):
                args.update(ph["detail"])
            trace.append({
                "name": ph["phase"], "cat": "serve", "ph": "X",
                "ts": ph["start"] * 1e6,
                "dur": max(ph["dur"] * 1e6, 1.0),
                "pid": pid_of(track), "tid": ph["phase"],
                "args": args,
            })
        for name, ts in row["marks"].items():
            trace.append({
                "name": name, "cat": "serve", "ph": "i",
                "ts": ts * 1e6, "s": "p",
                "pid": pid_of(f"replica {row['replica'] or '?'}"
                              if row["replica"] else f"proxy ({dep})"),
                "tid": "stream",
                "args": {"rid": row["rid"]},
            })
    return trace


class RequestAggregator:
    """GCS-side rolling serve-request metrics over successive cluster
    scrapes, plus the bounded record log the merged request view renders
    from (so the timeline survives the proxies/replicas that produced
    it — same posture as steptrace.SkewAggregator).

    Metric families on the host registry (riding the existing /metrics
    cluster scrape because the GCS snapshots itself):

    - ``serve_request_phase_seconds{app,deployment,phase}``: histogram
      of per-phase span durations — p50/p95/p99 per phase per
      deployment, the autoscaling/admission signals;
    - ``serve_request_ttft_seconds{app,deployment}``: streaming time to
      first byte (first_byte mark − request start).

    Dedup across scrapes: every record carries its process-monotonic
    ``idx``; records at or below the per-(node, pid) high-water mark
    were folded already.
    """

    def __init__(self, registry=None, log_limit: int = 65536):
        import threading
        from collections import OrderedDict, deque

        from ray_tpu._private import metrics_core

        reg = registry or metrics_core.registry()
        self.log: "deque[dict]" = deque(maxlen=log_limit)
        self._lock = threading.Lock()
        self._scrapes = 0
        self._hist = reg.histogram(
            "serve_request_phase_seconds",
            "serve request phase span durations, by deployment and phase",
            scale=metrics_core.LATENCY)
        self._ttft = reg.histogram(
            "serve_request_ttft_seconds",
            "streaming serve requests: time to first byte",
            scale=metrics_core.LATENCY)
        self._folded = reg.counter(
            "reqtrace_spans_folded_total",
            "serve request phase spans folded into metrics")
        # (node_id, pid) -> (max idx folded, last scrape seen, epoch)
        self._seen: Dict[tuple, tuple] = {}
        # rid -> earliest span start (TTFT pairing), bounded FIFO
        self._starts: "OrderedDict[str, float]" = OrderedDict()

    def fold(self, processes: Sequence[dict]) -> int:
        with self._lock:
            return self._fold_locked(processes)

    def _fold_locked(self, processes: Sequence[dict]) -> int:
        self._scrapes += 1
        folded = 0
        for proc in processes:
            if proc.get("error"):
                continue
            key = (proc.get("node_id"), proc.get("pid"))
            mark, _, seen_epoch = self._seen.get(key, (-1, 0, None))
            epoch = proc.get("epoch")
            recs = proc.get("records", ())
            # pid recycling: a NEW process behind an old (node, pid) key
            # must fold from scratch, not be discarded as already-folded.
            # The epoch token detects it exactly; the top-idx-below-mark
            # heuristic is kept for snapshots without one, but misses a
            # recycled process that already wrote past the dead one's mark
            snap_top = max((r.get("idx", 0) for r in recs), default=None)
            if (epoch is not None and epoch != seen_epoch
                    and seen_epoch is not None) or \
                    (snap_top is not None and snap_top < mark):
                mark = -1
            top = mark
            for rec in recs:
                idx = rec.get("idx", 0)
                if idx <= mark:
                    continue
                top = max(top, idx)
                self.log.append(rec)
                folded += self._fold_record(rec)
            self._seen[key] = (top, self._scrapes, epoch)
        if len(self._seen) > 1024:
            floor = self._scrapes - 64
            for key in [k for k, (_, s) in self._seen.items()
                        if s < floor]:
                del self._seen[key]
        if folded:
            self._folded.inc(folded)
        return folded

    def _fold_record(self, rec: dict) -> int:
        rid = rec.get("rid") or ""
        if rec.get("kind") == "span":
            self._hist.labels(
                app=rec.get("app") or "?",
                deployment=rec.get("deployment") or "?",
                phase=rec.get("phase") or "?",
            ).record(max(0.0, rec.get("end", 0.0) - rec.get("start", 0.0)))
            start = rec.get("start", 0.0)
            prev = self._starts.get(rid)
            if prev is None or start < prev:
                self._starts[rid] = start
                self._starts.move_to_end(rid)
            while len(self._starts) > 4096:
                self._starts.popitem(last=False)
            return 1
        if rec.get("kind") == "mark" and rec.get("name") == "first_byte":
            start = self._starts.get(rid)
            if start is not None:
                self._ttft.labels(
                    app=rec.get("app") or "?",
                    deployment=rec.get("deployment") or "?",
                ).record(max(0.0, rec.get("ts", 0.0) - start))
            return 1
        return 0

    def records(self) -> List[dict]:
        with self._lock:
            return list(self.log)

    def fold_and_merge(self, processes: Sequence[dict],
                       limit: int = 0) -> Dict[str, Any]:
        """One scrape's whole CPU-bound path — fold the snapshots, copy
        the bounded log, merge it — as a single call the GCS pushes onto
        an executor thread. ``limit`` caps the merge to the newest N
        records for cheap polling surfaces."""
        with self._lock:
            self._fold_locked(processes)
            records = list(self.log)
        if limit and len(records) > limit:
            records = records[-int(limit):]
        return merge_records(records)
