"""Node process orchestration.

Analog of ray: python/ray/_private/node.py:37 Node + services.py: starts and
owns the per-node processes (GCS on the head, a raylet per node), discovers
their ports via port files, and tears them down on shutdown. Sessions live
under /dev/shm when available so the object store's files are true shared
memory. Session layout (one dir per cluster session)::

    session_<ts>_<rand>/
      cluster_token            rpc auth token (0600)
      gcs_store.log            GCS persistence log
      logs/                    per-process stdout/stderr
      store_<node_id12>/       raylet object store (per node)
        index.shm              shared-memory object index (slab arena)
        slabs/seg_*.slab       leased slab segments (sparse tmpfs)
        <oid>.obj              one-file objects (spill restores, fallback)

The store dirs are tmpfs-backed shared memory: ``shutdown`` removes this
node's store dir so slab segments and mappings don't outlive the session
in /dev/shm (stale sessions would otherwise pin host memory until a
reboot).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Dict, Optional

DEFAULT_SESSION_ROOT = "/dev/shm/ray_tpu" if os.path.isdir("/dev/shm") else None


def _make_session_dir(session_root: Optional[str] = None) -> str:
    root = session_root or DEFAULT_SESSION_ROOT or os.path.join(
        tempfile.gettempdir(), "ray_tpu"
    )
    session_dir = os.path.join(root, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    return session_dir


def load_cluster_token(session_dir: Optional[str] = None) -> Optional[str]:
    """Load a persisted cluster token into the environment if unset.

    Tries, in order: an explicit ``session_dir/cluster_token``, then the CLI
    state file (~/.ray_tpu/cluster.json) token_file entry. Returns the token
    or None. No-op when RAY_TPU_CLUSTER_TOKEN is already exported.
    """
    if os.environ.get("RAY_TPU_CLUSTER_TOKEN"):
        return os.environ["RAY_TPU_CLUSTER_TOKEN"]
    candidates = []
    if session_dir:
        candidates.append(os.path.join(session_dir, "cluster_token"))
    state_file = os.path.expanduser("~/.ray_tpu/cluster.json")
    try:
        with open(state_file) as f:
            state = json.load(f)
        if state.get("token_file"):
            candidates.append(state["token_file"])
        if state.get("session_dir"):
            candidates.append(os.path.join(state["session_dir"], "cluster_token"))
    except (OSError, ValueError):
        pass
    for path in candidates:
        try:
            with open(path) as f:
                token = f.read().strip()
            if token:
                os.environ["RAY_TPU_CLUSTER_TOKEN"] = token
                return token
        except OSError:
            continue
    return None


def _wait_port_file(path: str, timeout: float = 30.0) -> list:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip().split("\n")
        time.sleep(0.05)
    raise TimeoutError(f"process did not write port file {path}")


def package_env(env: Optional[dict] = None) -> dict:
    """Env with PYTHONPATH including ray_tpu's parent dir, so subprocesses can
    import the package regardless of the caller's cwd/installation.

    Restores a TPU-plugin env var stashed by ``control_plane_env`` so
    WORKERS (spawned by the raylet with this env) keep the accelerator
    path even though their raylet runs without it."""
    env = dict(env if env is not None else os.environ)
    import ray_tpu

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    existing = env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        env["PYTHONPATH"] = pkg_root + (os.pathsep + existing if existing else "")
    stash = env.pop("RAY_TPU_TPU_PLUGIN_STASH", None)
    if (stash and "PALLAS_AXON_POOL_IPS" not in env
            and env.get("JAX_PLATFORMS") != "cpu"):
        # CPU-pinned processes (the test suite, CPU-only workers) skip
        # the TPU plugin re-registration — and with it sitecustomize's
        # multi-second jax import at interpreter boot
        env["PALLAS_AXON_POOL_IPS"] = stash
    return env


def control_plane_env(env: Optional[dict] = None) -> dict:
    """Spawn env for GCS/raylet processes: these never touch jax, but the
    environment's sitecustomize imports it (~2s of interpreter boot per
    process) whenever PALLAS_AXON_POOL_IPS is set. Strip the trigger —
    stashed so package_env restores it for worker spawns — and the
    control plane boots in a fraction of the time."""
    env = package_env(env)
    pool_ips = env.pop("PALLAS_AXON_POOL_IPS", None)
    if pool_ips:
        env["RAY_TPU_TPU_PLUGIN_STASH"] = pool_ips
    return env


def _spawn(cmd, log_path: str, env: dict) -> subprocess.Popen:
    """Callers build the env explicitly (control_plane_env for
    GCS/raylet/agents, package_env for anything that may use jax)."""
    out = open(log_path, "ab")
    return subprocess.Popen(cmd, stdout=out, stderr=subprocess.STDOUT, env=env)


class NodeProcesses:
    """Starts GCS (head only) + raylet subprocesses for one logical node."""

    def __init__(
        self,
        head: bool = True,
        gcs_host: str = "127.0.0.1",
        gcs_port: Optional[int] = None,
        session_dir: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.head = head
        if head and not os.environ.get("RAY_TPU_CLUSTER_TOKEN"):
            # Cluster-scoped RPC auth: every process spawned from here (and
            # every driver sharing this env) inherits the token; rpcio
            # rejects unauthenticated connects (see rpcio.py preamble).
            import secrets

            os.environ["RAY_TPU_CLUSTER_TOKEN"] = secrets.token_hex(16)
        self.session_dir = session_dir or _make_session_dir()
        # Persist the token (0600) so separately launched processes — the
        # CLI after `start --head`, drivers using init(address=...), worker
        # raylets joining via `start --address` on the same host — can load
        # it instead of silently failing auth. Cross-host joins still export
        # RAY_TPU_CLUSTER_TOKEN manually (the CLI prints the hint).
        token = os.environ.get("RAY_TPU_CLUSTER_TOKEN", "")
        if token:
            self.token_file = os.path.join(self.session_dir, "cluster_token")
            if not os.path.exists(self.token_file):
                fd = os.open(
                    self.token_file, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600
                )
                with os.fdopen(fd, "w") as f:
                    f.write(token)
        else:
            self.token_file = None
        self.logs = os.path.join(self.session_dir, "logs")
        os.makedirs(self.logs, exist_ok=True)
        self.gcs_host = gcs_host
        self.gcs_proc: Optional[subprocess.Popen] = None
        suffix = uuid.uuid4().hex[:8]
        self.gcs_persist_path = os.path.join(self.session_dir, "gcs_store.log")
        if head:
            port_file = os.path.join(self.session_dir, f"gcs_port_{suffix}")
            self.gcs_proc = _spawn(
                [sys.executable, "-m", "ray_tpu._private.gcs_main",
                 "--host", gcs_host, "--port", "0", "--port-file", port_file,
                 "--persist-path", self.gcs_persist_path,
                 "--cluster-id", os.path.basename(self.session_dir)],
                os.path.join(self.logs, "gcs.out"),
                env=control_plane_env(),
            )
            self.gcs_port = int(_wait_port_file(port_file)[0])
        else:
            assert gcs_port is not None
            self.gcs_port = gcs_port
        raylet_port_file = os.path.join(self.session_dir, f"raylet_port_{suffix}")
        cmd = [
            sys.executable, "-m", "ray_tpu._private.raylet_main",
            "--gcs-host", gcs_host, "--gcs-port", str(self.gcs_port),
            "--session-dir", self.session_dir,
            "--port-file", raylet_port_file,
        ]
        if resources is not None:
            cmd += ["--resources", json.dumps(resources)]
        if labels is not None:
            cmd += ["--labels", json.dumps(labels)]
        self.raylet_proc = _spawn(
            cmd, os.path.join(self.logs, f"raylet_{suffix}.out"),
            env=control_plane_env(),
        )
        lines = _wait_port_file(raylet_port_file)
        self.raylet_port = int(lines[0])
        self.node_id = lines[1] if len(lines) > 1 else None

    @property
    def address(self) -> str:
        return f"{self.gcs_host}:{self.gcs_port}"

    def kill_raylet(self, graceful: bool = False):
        """Chaos hook (analog of ray: _private/test_utils.py NodeKillerActor)."""
        if graceful:
            self.raylet_proc.terminate()
        else:
            self.raylet_proc.kill()
        self.raylet_proc.wait(timeout=10)

    # -- network chaos hooks (see _private/faultsim.py) -----------------
    # Every control-plane process spawned from here inherits
    # RAY_TPU_RPC_FAULTS / RAY_TPU_RPC_FAULTS_FILE through its env; the
    # FILE variant is re-read live, so faults can be armed and HEALED
    # while raylet/GCS subprocesses keep running. Export the env var
    # BEFORE building the cluster — children snapshot their env at spawn.

    def set_network_faults(self, spec: str):
        """(Re)write the live fault spec file. Requires
        RAY_TPU_RPC_FAULTS_FILE to have been exported before this node's
        processes started."""
        path = os.environ.get("RAY_TPU_RPC_FAULTS_FILE")
        assert path, (
            "export RAY_TPU_RPC_FAULTS_FILE before starting the cluster "
            "to use dynamic fault injection"
        )
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(spec)
        os.replace(tmp, path)  # atomic: readers never see a half-written spec

    def clear_network_faults(self):
        """Heal: remove every armed network fault."""
        self.set_network_faults("")

    def kill_gcs(self):
        """Chaos hook: kill the GCS process (head only). State survives in
        the persist log; ``restart_gcs`` brings it back on the same port."""
        assert self.gcs_proc is not None, "kill_gcs only valid on the head"
        self.gcs_proc.kill()
        self.gcs_proc.wait(timeout=10)

    def restart_gcs(self):
        """Restart the GCS on its original port; it replays the persist log
        and raylets/workers reconnect (ray: GCS FT via Redis restart +
        RayletNotifyGCSRestart)."""
        assert self.head, "restart_gcs only valid on the head"
        self.gcs_proc = _spawn(
            [sys.executable, "-m", "ray_tpu._private.gcs_main",
             "--host", self.gcs_host, "--port", str(self.gcs_port),
             "--persist-path", self.gcs_persist_path,
             "--cluster-id", os.path.basename(self.session_dir)],
            os.path.join(self.logs, "gcs.out"),
            env=control_plane_env(),
        )

    def shutdown(self):
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is None:
                continue
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is None:
                continue
            try:
                proc.wait(timeout=5)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        # release this node's share of /dev/shm: the store dir (slab
        # segments, index, .obj files) is dead weight once the raylet is
        # gone — processes still holding mappings keep their pages until
        # the views die, so this is safe for stragglers
        if self.node_id:
            import shutil

            shutil.rmtree(
                os.path.join(self.session_dir, f"store_{self.node_id[:12]}"),
                ignore_errors=True,
            )
