"""ctypes binding for the native (C++) object store.

Loads ``src/librtpu_store.so`` (building it with make on first use if a
toolchain is present) and exposes the same surface as the pure-Python
implementation in object_store.py. The runtime picks native when
available; set ``RAY_TPU_NATIVE_STORE=0`` to force the Python path.

Reference parity: this is the plasma-client boundary (ray:
src/ray/object_manager/plasma/client.h) collapsed to a C ABI — the data
plane stays mmap'd files in /dev/shm either way, so native and Python
processes interoperate on one store directory.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Iterable, Optional, Tuple

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "src",
)
_LIB_PATH = os.path.join(_SRC_DIR, "librtpu_store.so")

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _configure(lib):
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.rtpu_write_object.restype = ctypes.c_long
    lib.rtpu_write_object.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    lib.rtpu_open_object.restype = ctypes.c_void_p
    lib.rtpu_open_object.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rtpu_release_object.restype = None
    lib.rtpu_release_object.argtypes = [ctypes.c_void_p]
    lib.rtpu_object_exists.restype = ctypes.c_int
    lib.rtpu_object_exists.argtypes = [ctypes.c_char_p, ctypes.c_char_p]

    lib.rtpu_store_create.restype = ctypes.c_void_p
    lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rtpu_store_create2.restype = ctypes.c_void_p
    lib.rtpu_store_create2.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p
    ]
    lib.rtpu_store_restore.restype = ctypes.c_int
    lib.rtpu_store_restore.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_is_spilled.restype = ctypes.c_int
    lib.rtpu_store_is_spilled.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_spilled_bytes.restype = ctypes.c_uint64
    lib.rtpu_store_spilled_bytes.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_destroy.restype = None
    lib.rtpu_store_destroy.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_put.restype = ctypes.c_long
    lib.rtpu_store_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    for name in ("register_external", "touch", "pin", "unpin", "delete"):
        fn = getattr(lib, f"rtpu_store_{name}")
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_used.restype = ctypes.c_uint64
    lib.rtpu_store_used.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_count.restype = ctypes.c_uint64
    lib.rtpu_store_count.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_list.restype = ctypes.c_uint64
    lib.rtpu_store_list.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64
    ]
    # append-log KV store (GCS persistence; src/log_store.cpp). Optional:
    # a prebuilt .so without these symbols still serves the object store.
    u8pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
    u64p = ctypes.POINTER(ctypes.c_uint64)
    try:
        lib.rtpu_log_open
    except AttributeError:
        lib._has_log_store = False
        return lib
    lib._has_log_store = True
    lib.rtpu_log_open.restype = ctypes.c_void_p
    lib.rtpu_log_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.rtpu_log_put.restype = ctypes.c_int
    lib.rtpu_log_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.rtpu_log_count.restype = ctypes.c_uint64
    lib.rtpu_log_count.argtypes = [ctypes.c_void_p]
    lib.rtpu_log_iter_start.restype = None
    lib.rtpu_log_iter_start.argtypes = [ctypes.c_void_p]
    lib.rtpu_log_iter_next.restype = ctypes.c_int
    lib.rtpu_log_iter_next.argtypes = [
        ctypes.c_void_p, u8pp, u64p, u8pp, u64p, u8pp, u64p,
    ]
    lib.rtpu_log_close.restype = None
    lib.rtpu_log_close.argtypes = [ctypes.c_void_p]
    return lib


def load_library() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    if os.environ.get("RAY_TPU_NATIVE_STORE", "1") == "0":
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib

        def _stale() -> bool:
            """A .so older than any source is from a previous build and may
            be missing newer symbols — rebuild rather than crash on
            AttributeError during _configure."""
            if not os.path.exists(_LIB_PATH):
                return True
            lib_mtime = os.path.getmtime(_LIB_PATH)
            for name in os.listdir(_SRC_DIR):
                if name.endswith((".cpp", ".h")) and os.path.getmtime(
                    os.path.join(_SRC_DIR, name)
                ) > lib_mtime:
                    return True
            return False

        if _stale() and not _build_attempted:
            _build_attempted = True
            try:
                # Cross-process file lock: many workers starting at once
                # must not run concurrent builds of the same output (the
                # Makefile links to a temp name + atomic mv, so already-
                # mapped processes are safe either way).
                import fcntl

                with open(os.path.join(_SRC_DIR, ".build.lock"), "w") as lk:
                    fcntl.flock(lk, fcntl.LOCK_EX)
                    if _stale():  # may have been built while we waited
                        subprocess.run(
                            ["make", "-C", _SRC_DIR, "-s", "-B"],
                            check=True, capture_output=True, timeout=120,
                        )
            except Exception as e:  # no toolchain / build failure
                logger.debug("native store build failed: %s", e)
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            _lib = _configure(ctypes.CDLL(_LIB_PATH))
        except (OSError, AttributeError) as e:
            # AttributeError = stale .so missing newer symbols
            logger.warning("could not load native store: %s", e)
            return None
        return _lib


def available() -> bool:
    return load_library() is not None


def _buffer_pointers(metadata: bytes, buffers: Iterable):
    """(meta, bufs_array, lens_array, nbufs, keepalive) for a C call.

    Zero-copy for bytes and writable buffers; readonly non-bytes views are
    copied once (rare: big tensors expose writable buffers)."""
    keep = []
    ptrs = []
    lens = []
    for buf in buffers:
        if isinstance(buf, (bytes, bytearray)):
            ptrs.append(ctypes.cast(ctypes.c_char_p(bytes(buf) if isinstance(buf, bytearray) else buf), ctypes.c_void_p))
            keep.append(buf)
            lens.append(len(buf))
            continue
        mv = memoryview(buf)
        if mv.ndim != 1 or mv.format != "B":
            mv = mv.cast("B")
        if mv.readonly:
            b = bytes(mv)
            keep.append(b)
            ptrs.append(ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p))
            lens.append(len(b))
        else:
            c = (ctypes.c_char * len(mv)).from_buffer(mv)
            keep.append((mv, c))
            ptrs.append(ctypes.cast(ctypes.addressof(c), ctypes.c_void_p))
            lens.append(len(mv))
    n = len(ptrs)
    arr = (ctypes.c_void_p * n)(*ptrs)
    larr = (ctypes.c_uint64 * n)(*lens)
    return arr, larr, n, keep


def write_object(store_dir: str, oid_hex: str, metadata: bytes,
                 buffers: Iterable, total_data_len: int) -> int:
    lib = load_library()
    arr, larr, n, keep = _buffer_pointers(metadata, buffers)
    written = lib.rtpu_write_object(
        store_dir.encode(), oid_hex.encode(), metadata, len(metadata),
        arr, larr, n,
    )
    if written < 0:
        raise IOError(f"native write_object failed for {oid_hex}")
    return written


def open_object(store_dir: str, oid_hex: str
                ) -> Optional[Tuple[int, bytes, memoryview]]:
    """(handle, metadata, data_view) or None. Caller must release(handle)
    after the data view is no longer needed."""
    lib = load_library()
    meta_ptr = ctypes.c_void_p()
    meta_len = ctypes.c_uint64()
    data_ptr = ctypes.c_void_p()
    data_len = ctypes.c_uint64()
    handle = lib.rtpu_open_object(
        store_dir.encode(), oid_hex.encode(),
        ctypes.byref(meta_ptr), ctypes.byref(meta_len),
        ctypes.byref(data_ptr), ctypes.byref(data_len),
    )
    if not handle:
        return None
    metadata = ctypes.string_at(meta_ptr, meta_len.value)
    if data_len.value:
        carr = (ctypes.c_char * data_len.value).from_address(data_ptr.value)
        data = memoryview(carr)
    else:
        data = memoryview(b"")
    return handle, metadata, data


def release(handle: int):
    lib = load_library()
    lib.rtpu_release_object(ctypes.c_void_p(handle))


def object_exists(store_dir: str, oid_hex: str) -> bool:
    lib = load_library()
    return bool(lib.rtpu_object_exists(store_dir.encode(), oid_hex.encode()))


class NativeLocalObjectStore:
    """Owner-side accounting store backed by the C++ RtpuStore."""

    def __init__(self, store_dir: str, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        self._lib = load_library()
        assert self._lib is not None
        self.store_dir = store_dir
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        self._store = ctypes.c_void_p(
            self._lib.rtpu_store_create2(
                store_dir.encode(), capacity_bytes,
                (spill_dir or "").encode(),
            )
        )

    # mirror of object_store.LocalObjectStore -------------------------
    def put(self, object_id, metadata: bytes, buffers, total_data_len: int):
        from ray_tpu._private.object_store import ObjectStoreFullError

        arr, larr, n, keep = _buffer_pointers(metadata, buffers)
        rc = self._lib.rtpu_store_put(
            self._store, object_id.hex().encode(), metadata, len(metadata),
            arr, larr, n,
        )
        if rc == -2:
            raise ObjectStoreFullError(
                f"object does not fit: used={self.used_bytes()} "
                f"capacity={self.capacity} (all remaining objects pinned)"
            )
        if rc < 0:
            raise IOError(f"native store put failed for {object_id}")

    def register_external(self, object_id):
        self._lib.rtpu_store_register_external(
            self._store, object_id.hex().encode()
        )

    def get(self, object_id):
        from ray_tpu._private import object_store as pystore

        buf = pystore.read_object(self.store_dir, object_id)
        if buf is None and self.restore_if_spilled(object_id):
            buf = pystore.read_object(self.store_dir, object_id)
        if buf is not None:
            self._lib.rtpu_store_touch(self._store, object_id.hex().encode())
        return buf

    def contains(self, object_id) -> bool:
        return object_exists(self.store_dir, object_id.hex()) or bool(
            self._lib.rtpu_store_is_spilled(
                self._store, object_id.hex().encode()
            )
        )

    def restore_if_spilled(self, object_id) -> bool:
        return self._lib.rtpu_store_restore(
            self._store, object_id.hex().encode()
        ) == 1

    def spilled_stats(self):
        return {
            "spilled_bytes_total": int(
                self._lib.rtpu_store_spilled_bytes(self._store)
            ),
        }

    def pin(self, object_id):
        self._lib.rtpu_store_pin(self._store, object_id.hex().encode())

    def unpin(self, object_id):
        self._lib.rtpu_store_unpin(self._store, object_id.hex().encode())

    def delete(self, object_id):
        self._lib.rtpu_store_delete(self._store, object_id.hex().encode())

    def used_bytes(self) -> int:
        return int(self._lib.rtpu_store_used(self._store))

    def object_ids(self):
        from ray_tpu._private.ids import ObjectID

        n = int(self._lib.rtpu_store_count(self._store))
        if n == 0:
            return []
        buf = ctypes.create_string_buffer(65 * n)
        got = int(self._lib.rtpu_store_list(self._store, buf, n))
        out = []
        for i in range(got):
            hexid = buf.raw[i * 65 : (i + 1) * 65].split(b"\0", 1)[0].decode()
            out.append(ObjectID(bytes.fromhex(hexid)))
        return out
