"""Env-gated cProfile for system processes.

Set ``RAY_TPU_PROFILE_DIR=/some/dir`` before starting a cluster and every
system process (gcs, raylet, worker) profiles itself, dumping
``<role>-<pid>.pstats`` on exit — the offline analog of attaching py-spy
to the reference's C++ processes (which perf/gperftools would cover).
Zero overhead when the variable is unset.
"""

from __future__ import annotations

import atexit
import os


def maybe_profile(role: str, snapshot_interval_s: float = 5.0):
    """Enable process-wide profiling if RAY_TPU_PROFILE_DIR is set.

    Stats snapshot to disk every few seconds (and at exit): system
    processes die by SIGTERM→os._exit, which skips atexit hooks."""
    out_dir = os.environ.get("RAY_TPU_PROFILE_DIR")
    if not out_dir:
        return
    import cProfile
    import threading

    prof = cProfile.Profile()
    prof.enable()
    path = os.path.join(out_dir, f"{role}-{os.getpid()}.pstats")

    def dump():
        try:
            os.makedirs(out_dir, exist_ok=True)
            prof.create_stats()  # NB: internally disables the profiler
            prof.dump_stats(path)
        except Exception:
            pass
        finally:
            try:
                prof.enable()
            except Exception:
                pass

    def loop():
        import time

        while True:
            time.sleep(snapshot_interval_s)
            dump()

    threading.Thread(target=loop, name="profile-snap", daemon=True).start()
    atexit.register(dump)
