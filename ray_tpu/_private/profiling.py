"""Env-gated cProfile for system processes.

Set ``RAY_TPU_PROFILE_DIR=/some/dir`` before starting a cluster and every
system process (gcs, raylet, worker) profiles itself, dumping
``<role>-<pid>.pstats`` on exit — the offline analog of attaching py-spy
to the reference's C++ processes (which perf/gperftools would cover).
Zero overhead when the variable is unset.
"""

from __future__ import annotations

import atexit
import os


def enable_crash_diagnostics():
    """faulthandler for every system process: fatal signals print all
    thread stacks to stderr (→ the process's session log), and SIGUSR1
    dumps stacks WITHOUT dying — the attach-a-debugger analog for
    diagnosing a wedged gcs/raylet/worker in place (ray parity:
    `ray stack`, which py-spy-dumps live processes)."""
    import faulthandler
    import signal

    try:
        faulthandler.enable()
        faulthandler.register(signal.SIGUSR1, all_threads=True, chain=False)
        signal.signal(signal.SIGUSR2, _dump_asyncio_tasks)
    except Exception:
        pass  # non-main-thread import or exotic platform: diagnostics only


def all_asyncio_tasks() -> list:
    """Every live asyncio task across ALL loops/threads in this process.
    ``asyncio.all_tasks()`` needs a running loop on the calling thread;
    the interpreter-wide registry moved between versions: 3.12 keeps
    WeakSets in the C module (``_asyncio._scheduled_tasks`` /
    ``_eager_tasks``), older versions in ``asyncio.tasks._all_tasks``."""
    try:
        import _asyncio

        tasks = list(getattr(_asyncio, "_scheduled_tasks", ()))
        tasks += list(getattr(_asyncio, "_eager_tasks", ()))
        if tasks:
            return tasks
    except ImportError:
        pass
    import asyncio

    return list(getattr(asyncio.tasks, "_all_tasks", ()))


def _dump_asyncio_tasks(signum=None, frame=None):
    """SIGUSR2: print every pending asyncio task's coroutine stack to
    stderr. Thread dumps (SIGUSR1) show event loops idle in select() no
    matter what their TASKS are wedged on — this is the view that actually
    localizes a stuck handler. Uses the interpreter-wide task registry so
    loops on non-main threads (worker EventLoopThread) are included."""
    import sys
    import traceback

    print(f"=== asyncio task dump pid={os.getpid()} ===", file=sys.stderr)
    try:
        tasks = all_asyncio_tasks()
    except Exception as e:  # registry is private: degrade, don't die
        print(f"(task registry unavailable: {e!r})", file=sys.stderr)
        tasks = []
    for t in tasks:
        try:
            if t.done():
                continue
            print(f"--- {t!r} ---", file=sys.stderr)
            t.print_stack(file=sys.stderr)
        except Exception:
            traceback.print_exc()
    print("=== end asyncio task dump ===", file=sys.stderr)
    sys.stderr.flush()


def maybe_profile_thread(role: str, snapshot_interval_s: float = 5.0):
    """Profile THE CALLING THREAD if RAY_TPU_PROFILE_DIR is set (cProfile
    instruments only the enabling thread). For loops hosted off-main —
    the driver's EventLoopThread — where ``maybe_profile`` on the main
    thread sees nothing but lock waits."""
    out_dir = os.environ.get("RAY_TPU_PROFILE_DIR")
    if not out_dir:
        return
    import cProfile
    import threading
    import time

    prof = cProfile.Profile()
    try:
        prof.enable()
    except ValueError:
        # 3.12 profiles process-wide: a system process that already runs
        # maybe_profile() covers this thread — a second profiler would
        # raise and kill the enabling thread (observed: worker io loop)
        return
    path = os.path.join(out_dir, f"{role}-{os.getpid()}.pstats")

    def dump():
        try:
            os.makedirs(out_dir, exist_ok=True)
            prof.create_stats()
            prof.dump_stats(path)
        except Exception:
            pass
        finally:
            try:
                prof.enable()
            except Exception:
                pass

    def loop():
        while True:
            time.sleep(snapshot_interval_s)
            dump()

    threading.Thread(target=loop, name=f"profile-snap-{role}",
                     daemon=True).start()
    atexit.register(dump)


def maybe_profile(role: str, snapshot_interval_s: float = 5.0):
    """Enable process-wide profiling if RAY_TPU_PROFILE_DIR is set.

    Stats snapshot to disk every few seconds (and at exit): system
    processes die by SIGTERM→os._exit, which skips atexit hooks."""
    enable_crash_diagnostics()
    out_dir = os.environ.get("RAY_TPU_PROFILE_DIR")
    if not out_dir:
        return
    import cProfile
    import threading

    prof = cProfile.Profile()
    prof.enable()
    path = os.path.join(out_dir, f"{role}-{os.getpid()}.pstats")

    def dump():
        try:
            os.makedirs(out_dir, exist_ok=True)
            prof.create_stats()  # NB: internally disables the profiler
            prof.dump_stats(path)
        except Exception:
            pass
        finally:
            try:
                prof.enable()
            except Exception:
                pass

    def loop():
        import time

        while True:
            time.sleep(snapshot_interval_s)
            dump()

    threading.Thread(target=loop, name="profile-snap", daemon=True).start()
    atexit.register(dump)
