"""Shared runtime data structures: task/actor specs, resources, policies.

TaskSpec mirrors the reference's TaskSpecification
(ray: src/ray/common/task/task_spec.h) — everything a raylet needs to
schedule and a worker needs to execute. Resource maps are plain
``{name: float}`` dicts with 4-decimal fixed-point semantics
(ray: src/ray/common/scheduling/fixed_point.h). Scheduling policies mirror
ray: src/ray/raylet/scheduling/policy/ (hybrid pack/spread, spread,
node-affinity, placement-group bundle PACK/SPREAD/STRICT_*).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

RESOURCE_QUANT = 1e-4  # 4-decimal fixed point


def quantize(v: float) -> float:
    return round(v / RESOURCE_QUANT) * RESOURCE_QUANT


def res_fits(demand: Dict[str, float], available: Dict[str, float]) -> bool:
    for k, v in demand.items():
        if v > available.get(k, 0.0) + RESOURCE_QUANT / 2:
            return False
    return True


def res_sub(avail: Dict[str, float], demand: Dict[str, float]):
    for k, v in demand.items():
        avail[k] = quantize(avail.get(k, 0.0) - v)


def res_add(avail: Dict[str, float], demand: Dict[str, float]):
    for k, v in demand.items():
        avail[k] = quantize(avail.get(k, 0.0) + v)


# Placement-group bundle resources are expressed as renamed resources on the
# hosting node, like the reference's formatted resources
# (ray: src/ray/common/placement_group.h FormatPlacementGroupResource).
def pg_resource_name(base: str, pg_id_hex: str, bundle_index: Optional[int]) -> str:
    if bundle_index is None:
        return f"{base}_group_{pg_id_hex}"
    return f"{base}_group_{bundle_index}_{pg_id_hex}"


def rewrite_resources_for_pg(
    resources: Dict[str, float], pg_id_hex: str, bundle_index: Optional[int]
) -> Dict[str, float]:
    out = {}
    for k, v in resources.items():
        out[pg_resource_name(k, pg_id_hex, bundle_index)] = v
        if bundle_index is not None:
            out[pg_resource_name(k, pg_id_hex, None)] = v
    return out


@dataclass
class SchedulingStrategy:
    """DEFAULT | SPREAD | node affinity | node label | placement group."""

    kind: str = "DEFAULT"
    node_id: Optional[str] = None  # NodeAffinity
    soft: bool = False
    pg_id: Optional[str] = None  # PlacementGroup
    pg_bundle_index: Optional[int] = None
    pg_capture_child_tasks: bool = False
    # NodeLabel (ray: node_label_scheduling_policy.h:25): {key: cond} where
    # cond is a str (equals), "!v" (not equals), a list (in), None (exists).
    labels_hard: Optional[Dict[str, Any]] = None
    labels_soft: Optional[Dict[str, Any]] = None


@dataclass
class TaskSpec:
    task_id: bytes
    job_id: bytes
    name: str
    # Function payload: cloudpickled callable, or (actor) method name.
    func_blob: Optional[bytes]
    method_name: Optional[str]
    # Args: list of ("v", serialized bytes) inline values or ("r", id_bytes,
    # owner) object refs; kwargs same encoding by key.
    args: List[Tuple] = field(default_factory=list)
    kwargs: Dict[str, Tuple] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    owner: Optional[tuple] = None  # (node_id_hex, client_id_hex)
    max_retries: int = 3
    retry_exceptions: bool = False
    # Actor fields
    actor_id: Optional[bytes] = None  # set for actor tasks
    actor_creation: bool = False
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
    # Which declared concurrency group this actor task runs under (None =
    # the default group, capped by max_concurrency). ray parity:
    # src/ray/core_worker/transport/concurrency_group_manager.h
    concurrency_group: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"
    name_registered: Optional[str] = None  # named actor
    namespace: Optional[str] = None
    runtime_env: Optional[dict] = None
    seq_no: int = 0  # per-caller actor-task ordering
    caller_id: Optional[bytes] = None
    attempt: int = 0
    # Times this task was re-executed to recover a lost return object
    # (ray: object_recovery_manager.h lineage reconstruction budget).
    reconstructions: int = 0
    submit_time: float = field(default_factory=time.time)
    # Propagated tracing context {trace_id, span_id} (ray:
    # tracing_helper.py:105-226 injects span context into task calls).
    tracing_ctx: Optional[dict] = None
    # Node that last spilled this task to its current location; that node
    # tracks the task and resubmits it if the executing node dies
    # (plays the reference's owner-side lease-failure retry role for the
    # fire-and-forget spillback flow).
    origin_node: Optional[str] = None

    def scheduling_class(self) -> tuple:
        return (tuple(sorted(self.resources.items())), self.name)


@dataclass
class NodeInfo:
    node_id: str  # hex
    host: str
    port: int  # raylet rpc port
    store_dir: str
    resources_total: Dict[str, float] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    # Dynamic view (updated by heartbeats):
    resources_available: Dict[str, float] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    # Autoscaler inputs (ray: monitor.proto ResourceLoad):
    pending_demand: list = field(default_factory=list)
    idle: bool = False
    idle_since: float = 0.0


# ---------------------------------------------------------------------------
# Scheduling policies (cluster-level node selection).
# ---------------------------------------------------------------------------


def _score(node: NodeInfo, demand: Dict[str, float]) -> float:
    """Least-resource scorer: lower = more utilized after placing.

    Mirrors LeastResourceScorer (ray: src/ray/raylet/scheduling/policy/scorer.h:41):
    score each resource by remaining fraction, prefer nodes that stay balanced.
    """
    scores = []
    for k, total in node.resources_total.items():
        if total <= 0:
            continue
        avail = node.resources_available.get(k, 0.0) - demand.get(k, 0.0)
        scores.append(max(avail, 0.0) / total)
    return sum(scores) / len(scores) if scores else 0.0


def pick_node_hybrid(
    nodes: List[NodeInfo],
    demand: Dict[str, float],
    local_node_id: Optional[str],
    spread_threshold: float = 0.5,
) -> Optional[str]:
    """Hybrid pack/spread (ray: hybrid_scheduling_policy.h:50): prefer the
    local node, then pack onto nodes below the critical-utilization threshold
    in traversal order, else pick the least-utilized feasible node."""
    feasible = [n for n in nodes if n.alive and res_fits(demand, _total(n))]
    if not feasible:
        return None
    ordered = sorted(feasible, key=lambda n: (n.node_id != local_node_id, n.node_id))
    best, best_score = None, -1.0
    for n in ordered:
        if not res_fits(demand, n.resources_available):
            continue
        util = 1.0 - _score(n, {})
        if util <= spread_threshold:
            return n.node_id
        sc = _score(n, demand)
        if sc > best_score:
            best, best_score = n.node_id, sc
    return best


def pick_node_spread(
    nodes: List[NodeInfo], demand: Dict[str, float], rr_state: List[int]
) -> Optional[str]:
    """Round-robin over available nodes (ray: spread_scheduling_policy.h:27)."""
    feasible = sorted(
        (n for n in nodes if n.alive and res_fits(demand, n.resources_available)),
        key=lambda n: n.node_id,
    )
    if not feasible:
        feasible = sorted(
            (n for n in nodes if n.alive and res_fits(demand, _total(n))),
            key=lambda n: n.node_id,
        )
    if not feasible:
        return None
    rr_state[0] = (rr_state[0] + 1) % len(feasible)
    return feasible[rr_state[0]].node_id


def _total(n: NodeInfo) -> Dict[str, float]:
    return n.resources_total


def _label_match(labels: Dict[str, str], selector: Optional[Dict[str, Any]]) -> bool:
    """Evaluate a label selector: str = equals, "!v" = not-equals, list =
    in, None = exists (ray: node_label_scheduling_policy.h In/NotIn/Exists).

    Label values are strings by construction; conditions are coerced to
    str so e.g. hard={"slice": [1, 2]} matches a node labeled "1"."""
    if not selector:
        return True
    for k, cond in selector.items():
        v = labels.get(k)
        if cond is None:
            if v is None:
                return False
        elif isinstance(cond, (list, tuple, set)):
            if v is None or v not in {str(c) for c in cond}:
                return False
        elif isinstance(cond, str) and cond.startswith("!"):
            if v == cond[1:]:
                return False
        else:
            if v != str(cond):
                return False
    return True


def pick_node_labels(
    nodes: List[NodeInfo],
    demand: Dict[str, float],
    hard: Optional[Dict[str, Any]],
    soft: Optional[Dict[str, Any]],
) -> Optional[str]:
    """Node-label policy (ray: node_label_scheduling_policy.h:25): hard
    selector filters; prefer soft-matching nodes with available capacity,
    then any available, then any feasible-by-total; least-utilized wins."""
    cands = [
        n for n in nodes
        if n.alive and _label_match(n.labels, hard)
        and res_fits(demand, n.resources_total)
    ]
    if not cands:
        return None
    avail = [n for n in cands if res_fits(demand, n.resources_available)]
    pref = [n for n in avail if _label_match(n.labels, soft)]
    pool = pref or avail or cands
    best, best_score = None, -2.0
    for n in sorted(pool, key=lambda n: n.node_id):
        sc = _score(n, demand)
        if sc > best_score:
            best, best_score = n.node_id, sc
    return best


def pick_node_py(
    nodes: List[NodeInfo],
    spec_resources: Dict[str, float],
    strategy: SchedulingStrategy,
    local_node_id: Optional[str],
    rr_state: List[int],
    spread_threshold: float = 0.5,
) -> Optional[str]:
    """Pure-Python policy dispatch — the oracle the native engine must match."""
    if strategy.kind == "NODE_AFFINITY":
        for n in nodes:
            if n.node_id == strategy.node_id and n.alive:
                if res_fits(spec_resources, n.resources_total):
                    return n.node_id
        if strategy.soft:
            return pick_node_hybrid(nodes, spec_resources, local_node_id, spread_threshold)
        return None
    if strategy.kind == "NODE_LABEL":
        return pick_node_labels(
            nodes, spec_resources, strategy.labels_hard, strategy.labels_soft
        )
    if strategy.kind == "SPREAD":
        return pick_node_spread(nodes, spec_resources, rr_state)
    return pick_node_hybrid(nodes, spec_resources, local_node_id, spread_threshold)


def pick_node(
    nodes: List[NodeInfo],
    spec_resources: Dict[str, float],
    strategy: SchedulingStrategy,
    local_node_id: Optional[str],
    rr_state: List[int],
    spread_threshold: float = 0.5,
) -> Optional[str]:
    from ray_tpu._private import native_sched

    if native_sched.available() and native_sched.encodable(
        nodes, spec_resources, strategy
    ):
        return native_sched.pick_node(
            nodes, spec_resources, strategy, local_node_id, rr_state,
            spread_threshold,
        )
    return pick_node_py(
        nodes, spec_resources, strategy, local_node_id, rr_state,
        spread_threshold,
    )


# ---------------------------------------------------------------------------
# Placement-group bundle placement (ray: policy/bundle_scheduling_policy.h:82-106)
# ---------------------------------------------------------------------------


def place_bundles(
    nodes: List[NodeInfo], bundles: List[Dict[str, float]], strategy: str,
    topology=None, committed_rings=None, max_candidates=None,
) -> Optional[List[str]]:
    """Return node_id per bundle, or None if infeasible.

    ``topology``/``committed_rings`` (topology.py) thread the contention
    scorer through this wrapper: when the cluster advertises torus
    coordinates, candidates are torus-aligned contiguous slices scored
    by ring overlap against already-committed gangs. Topology-less
    clusters (the default: topology=None, or no coords advertised) take
    the resource-fit path below — native engine or Python oracle —
    byte-identical to before the scorer existed."""
    if topology is not None:
        from ray_tpu._private import topology as topo_mod

        if max_candidates is None:
            # live clusters take the config knob; schedsim passes its
            # SimSpec value explicitly so a trace's byte-identity never
            # depends on ambient process config
            from ray_tpu._private.config import GLOBAL_CONFIG as cfg

            max_candidates = cfg.sched_max_candidates
        scored = topo_mod.place_bundles_topo(
            nodes, bundles, strategy, topology, committed_rings or {},
            max_candidates=max_candidates,
        )
        return None if scored is None else scored[0]
    from ray_tpu._private import native_sched

    if native_sched.available() and native_sched.encodable(
        nodes, {}, bundles=bundles
    ):
        return native_sched.place_bundles(nodes, bundles, strategy)
    return place_bundles_py(nodes, bundles, strategy)


def place_bundles_py(
    nodes: List[NodeInfo], bundles: List[Dict[str, float]], strategy: str
) -> Optional[List[str]]:
    """Pure-Python bundle placement — the oracle the native engine must match."""
    alive = [n for n in nodes if n.alive]
    avail = {n.node_id: dict(n.resources_available) for n in alive}

    def fits_and_take(nid, b):
        if res_fits(b, avail[nid]):
            res_sub(avail[nid], b)
            return True
        return False

    placement: List[Optional[str]] = [None] * len(bundles)
    order = sorted(range(len(bundles)), key=lambda i: -sum(bundles[i].values()))
    if strategy == "STRICT_PACK":
        for n in alive:
            tmp = dict(avail[n.node_id])
            ok = True
            for b in bundles:
                if res_fits(b, tmp):
                    res_sub(tmp, b)
                else:
                    ok = False
                    break
            if ok:
                return [n.node_id] * len(bundles)
        return None
    if strategy == "STRICT_SPREAD":
        used = set()
        for i in order:
            placed = False
            for n in sorted(alive, key=lambda n: n.node_id):
                if n.node_id in used:
                    continue
                if fits_and_take(n.node_id, bundles[i]):
                    placement[i] = n.node_id
                    used.add(n.node_id)
                    placed = True
                    break
            if not placed:
                return None
        return placement  # type: ignore[return-value]
    # PACK: prefer fewest nodes; SPREAD: prefer distinct nodes but allow reuse.
    prefer_distinct = strategy == "SPREAD"
    used: set = set()
    for i in order:
        candidates = sorted(alive, key=lambda n: ((n.node_id in used) == prefer_distinct, n.node_id))
        placed = False
        for n in candidates:
            if fits_and_take(n.node_id, bundles[i]):
                placement[i] = n.node_id
                used.add(n.node_id)
                placed = True
                break
        if not placed:
            return None
    return placement  # type: ignore[return-value]
