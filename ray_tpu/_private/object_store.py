"""Shared-memory local object store (plasma analog).

The reference's plasma store (ray: src/ray/object_manager/plasma/store.h) is a
shm arena with create/seal/get/release and LRU eviction; workers map segments
read-only for zero-copy reads. Here each sealed object is a file in a
``/dev/shm``-backed session directory mapped with ``mmap``:

  layout:  [8B magic][8B metadata_len][8B data_len][metadata][data]

Writers create ``<id>.building`` then atomically rename to ``<id>.obj`` on
seal, so any process on the node can open + mmap a sealed object without
talking to a broker: the data plane is the kernel page cache, exactly one
copy per node. Accounting (capacity, pinning, LRU eviction) is done by the
raylet process that owns the store directory; readers in other processes only
open/mmap.

A C++ implementation with the same on-disk format can replace the
writer/accounting path without changing readers.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Iterable, Optional

from ray_tpu._private.ids import ObjectID

_MAGIC = b"RTPUOBJ1"
_HEADER = 24

# --- runtime metrics (metrics_core.py) ---------------------------------
# Built lazily; read_object/write_object run in every process (workers
# write returns directly, raylets serve pulls), so each process's
# registry sees its own share and the cluster scrape merges them.
_MX = None


class _StoreMetrics:
    __slots__ = ("put_lat", "put_bytes", "get_lat", "get_bytes",
                 "ext_hits", "ext_misses", "spills", "restores")

    def __init__(self):
        from ray_tpu._private import metrics_core as mc

        reg = mc.registry()
        self.put_lat = reg.histogram(
            "object_store_put_latency_seconds",
            "Object create+seal latency", scale=mc.LATENCY).default
        self.put_bytes = reg.histogram(
            "object_store_put_bytes", "Object sizes written",
            scale=mc.SIZE).default
        self.get_lat = reg.histogram(
            "object_store_get_latency_seconds",
            "Object open+mmap latency", scale=mc.LATENCY).default
        self.get_bytes = reg.histogram(
            "object_store_get_bytes", "Object sizes mapped",
            scale=mc.SIZE).default
        self.ext_hits = reg.counter(
            "object_store_external_probe_hits_total",
            "External spill-backend existence probes that hit").default
        self.ext_misses = reg.counter(
            "object_store_external_probe_misses_total",
            "External spill-backend existence probes that missed").default
        self.spills = reg.counter(
            "object_store_spills_total", "Objects spilled out of shm").default
        self.restores = reg.counter(
            "object_store_restores_total",
            "Objects restored from the spill backend").default


def _mx() -> "_StoreMetrics":
    global _MX
    if _MX is None:
        _MX = _StoreMetrics()
    return _MX


class ObjectStoreFullError(Exception):
    pass


@dataclass
class ObjectBuffer:
    """A sealed object mapped into this process (zero-copy views)."""

    object_id: ObjectID
    metadata: bytes
    data: memoryview
    _mmap: mmap.mmap = None
    _file: object = None

    def release(self):
        if self._mmap is not None:
            try:
                self.data.release()
            except BufferError:
                pass
            try:
                self._mmap.close()
            except BufferError:
                # zero-copy slices of the data are still exported (e.g. a
                # chunk view queued on an rpc frame): the mapping closes
                # when the last view dies — refcounting, so promptly
                self._mmap = None
                return
            self._file.close()
            self._mmap = None


def _obj_path(store_dir: str, object_id: ObjectID) -> str:
    return os.path.join(store_dir, object_id.hex() + ".obj")


def read_object(store_dir: str, object_id: ObjectID) -> Optional[ObjectBuffer]:
    """Open and mmap a sealed object. Returns None if absent. Any process.

    Readers hold a SHARED flock on the file for the buffer's lifetime —
    the free path's page-recycling pool takes a non-blocking EXCLUSIVE
    flock before recycling, so pages a live zero-copy view still maps can
    never be rewritten; the pool falls back to unlink (inode stays intact
    for existing mappings). The post-lock inode recheck closes the
    open->lock race against a concurrent pool rename."""
    import fcntl

    t0 = time.perf_counter()
    path = _obj_path(store_dir, object_id)
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return None
    try:
        fcntl.flock(f.fileno(), fcntl.LOCK_SH)
        if os.fstat(f.fileno()).st_ino != os.stat(path).st_ino:
            f.close()  # pooled/recycled between open and lock: gone
            return None
    except OSError:
        f.close()
        return None
    m = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    if m[:8] != _MAGIC:
        m.close()
        f.close()
        raise IOError(f"corrupt object {object_id}")
    meta_len = int.from_bytes(m[8:16], "little")
    data_len = int.from_bytes(m[16:24], "little")
    metadata = bytes(m[_HEADER : _HEADER + meta_len])
    data = memoryview(m)[_HEADER + meta_len : _HEADER + meta_len + data_len]
    mx = _mx()
    mx.get_lat.record(time.perf_counter() - t0)
    mx.get_bytes.record(data_len)
    return ObjectBuffer(object_id, metadata, data, _mmap=m, _file=f)


def object_exists(store_dir: str, object_id: ObjectID) -> bool:
    return os.path.exists(_obj_path(store_dir, object_id))


def write_object(
    store_dir: str,
    object_id: ObjectID,
    metadata: bytes,
    buffers: Iterable,
    total_data_len: int,
) -> int:
    """Create + seal an object from buffers. Returns bytes written.

    Safe from any process; accounting is reconciled by the owning store's
    directory scan. Writing an already-sealed id is a no-op (objects are
    immutable, so double-writes are benign).
    """
    final = _obj_path(store_dir, object_id)
    if os.path.exists(final):
        return 0
    t0 = time.perf_counter()
    from ray_tpu._private import native_store

    if native_store.available():
        written = native_store.write_object(
            store_dir, object_id.hex(), metadata, buffers, total_data_len
        )
        if written:
            mx = _mx()
            mx.put_lat.record(time.perf_counter() - t0)
            mx.put_bytes.record(total_data_len)
        return written
    tmp = final + f".building.{os.getpid()}"
    size = _HEADER + len(metadata) + total_data_len
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(len(metadata).to_bytes(8, "little"))
        f.write(total_data_len.to_bytes(8, "little"))
        f.write(metadata)
        for buf in buffers:
            f.write(buf)
    os.rename(tmp, final)
    mx = _mx()
    mx.put_lat.record(time.perf_counter() - t0)
    mx.put_bytes.record(total_data_len)
    return size


def make_local_store(store_dir: str, capacity_bytes: int,
                     spill_dir: Optional[str] = None):
    """Owner-side store factory: native C++ store (src/librtpu_store.so)
    when loadable, else the pure-Python implementation. Both share the
    same on-disk format, so mixed clusters interoperate. ``spill_dir``
    is a path OR a storage URI (ray: local_object_manager.h:40 +
    external_storage.py): file:///bare paths spill to disk — the native
    store's in-C++ fast path; other schemes (s3://, test-registered)
    route through the Python store's pluggable driver."""
    from ray_tpu._private import native_store
    from ray_tpu._private.external_storage import is_local_spill_uri

    if native_store.available() and is_local_spill_uri(spill_dir):
        from urllib.parse import urlparse

        local = urlparse(spill_dir).path if (
            spill_dir and spill_dir.startswith("file://")
        ) else spill_dir
        return native_store.NativeLocalObjectStore(
            store_dir, capacity_bytes, local
        )
    return LocalObjectStore(store_dir, capacity_bytes, spill_dir)


class LocalObjectStore:
    """Owner-side store accounting: capacity, pinning, LRU eviction.

    Runs inside the raylet (one per node). Mirrors the reference's
    ObjectLifecycleManager + EvictionPolicy
    (ray: src/ray/object_manager/plasma/object_lifecycle_manager.h:101,
    eviction_policy.h:160).
    """

    def __init__(self, store_dir: str, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        # URI-pluggable spill backend (ray parity: external_storage.py);
        # a bare path / file:// is the classic spill-to-disk
        from ray_tpu._private.external_storage import make_external_storage

        self._external = make_external_storage(spill_dir)
        self._lock = threading.Lock()
        self._sizes: Dict[ObjectID, int] = {}
        self._lru: "OrderedDict[ObjectID, float]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._used = 0
        self._spilled: Dict[ObjectID, int] = {}  # oid -> size on disk
        # restored-from-external objects whose backend copy still exists
        # (cleaned at delete); and oids whose one restart-recovery probe
        # already missed (never probe the backend again for them)
        self._ever_spilled: set = set()
        self._probe_missed: set = set()
        self.spilled_bytes_total = 0
        self.restored_bytes_total = 0

    # -- write path ----------------------------------------------------------
    def put(self, object_id: ObjectID, metadata: bytes, buffers, total_data_len: int):
        size = _HEADER + len(metadata) + total_data_len
        self._ensure_space(size)
        written = write_object(self.store_dir, object_id, metadata, buffers, total_data_len)
        if written:
            with self._lock:
                self._sizes[object_id] = written
                self._used += written
                self._lru[object_id] = time.monotonic()
                # the id exists now: a previously-cached miss must not
                # mask a later spill-restore of this object
                self._probe_missed.discard(object_id)

    def register_external(self, object_id: ObjectID):
        """Account for an object written directly by a worker process —
        this is how MOST objects enter the store, so capacity is enforced
        here too (spilling older objects to make room; the new object is
        already on shm, so the budget is made around it)."""
        path = _obj_path(self.store_dir, object_id)
        try:
            size = os.path.getsize(path)
        except FileNotFoundError:
            return
        with self._lock:
            self._probe_missed.discard(object_id)
            if object_id not in self._sizes:
                try:
                    self._ensure_space_locked(size)
                except ObjectStoreFullError:
                    pass  # already written: track the overshoot honestly
                self._sizes[object_id] = size
                self._used += size
                self._lru[object_id] = time.monotonic()

    # -- read path -----------------------------------------------------------
    def get(self, object_id: ObjectID) -> Optional[ObjectBuffer]:
        buf = read_object(self.store_dir, object_id)
        if buf is None and (object_id in self._spilled
                            or self._external is not None):
            # second disjunct = restart recovery: a fresh raylet's ledger
            # doesn't know what its predecessor spilled externally
            if self.restore_if_spilled(object_id):
                buf = read_object(self.store_dir, object_id)
        if buf is not None:
            with self._lock:
                if object_id in self._lru:
                    self._lru.move_to_end(object_id)
        return buf

    def contains(self, object_id: ObjectID) -> bool:
        if object_exists(self.store_dir, object_id) \
                or object_id in self._spilled:
            return True
        if self._external is None or object_id in self._probe_missed:
            return False
        try:
            found = self._external.exists(self._spill_key(object_id))
        except Exception:
            found = False
        (_mx().ext_hits if found else _mx().ext_misses).inc()
        if not found:
            # at most ONE external round trip per unseen id (the restore
            # path's contract): a routine containment check for an object
            # living on another node must not pay a backend probe forever.
            # Cleared when the object actually lands here (put /
            # register_external).
            with self._lock:
                if len(self._probe_missed) > 100_000:
                    self._probe_missed.clear()
                self._probe_missed.add(object_id)
        return found

    # -- spilling (ray: local_object_manager.h SpillObjects/restore) ---------
    @staticmethod
    def _spill_key(object_id: ObjectID) -> str:
        # deterministic, node-independent: a restarted raylet (new node
        # id) can restore a predecessor's externally-spilled objects
        return object_id.hex() + ".obj"

    def _spill_locked(self, object_id: ObjectID) -> bool:
        """Move one object's file from shm to the external backend; the
        object stays addressable and is restored on access. Pin counts
        survive: a spilled primary copy is still the primary copy."""
        src = _obj_path(self.store_dir, object_id)
        size = self._sizes.get(object_id, 0)
        try:
            self._external.spill(self._spill_key(object_id), src)
            os.unlink(src)
        except Exception:
            return False  # backend errors (boto, plugin) degrade to no-spill
        self._sizes.pop(object_id, None)
        self._lru.pop(object_id, None)
        self._used -= size
        self._spilled[object_id] = size
        self.spilled_bytes_total += size
        _mx().spills.inc()
        return True

    def restore_if_spilled(self, object_id: ObjectID) -> bool:
        """Bring a spilled object back into shm (ray:
        spilled_object_reader.h — we restore whole objects).

        The EXTERNAL copy is deliberately left in place: objects are
        immutable, so with a shared backend (s3) another raylet may
        restore the same key concurrently — deleting on restore would
        destroy a peer's only spilled copy and strand its ledger. The
        external copy is cleaned when the OBJECT is deleted (refcount
        zero), tracked via _ever_spilled."""
        with self._lock:
            size = self._spilled.get(object_id)
            untracked = size is None
            if untracked:
                if self._external is None:
                    return False
                # restart-recovery probe: at most ONE external lookup per
                # unseen oid — a routine miss for an object living on
                # another node must not pay a backend round trip forever
                if object_id in self._probe_missed:
                    return False
            else:
                try:
                    self._ensure_space_locked(size)
                except ObjectStoreFullError:
                    return False
            dst = _obj_path(self.store_dir, object_id)
            try:
                ok = self._external.restore(
                    self._spill_key(object_id), dst
                )
            except Exception:
                ok = False  # backend errors (boto, plugin) degrade to miss
            if not ok:
                if untracked:
                    if len(self._probe_missed) > 100_000:
                        self._probe_missed.clear()
                    self._probe_missed.add(object_id)
                return False
            if untracked:
                # a predecessor raylet spilled this object; its size
                # wasn't in our (fresh) ledger — the file is already on
                # shm, so a full store tracks the overshoot honestly
                try:
                    size = os.path.getsize(dst)
                except OSError:
                    size = 0
                try:
                    self._ensure_space_locked(size)
                except ObjectStoreFullError:
                    pass
            self._spilled.pop(object_id, None)
            self._ever_spilled.add(object_id)
            self._sizes[object_id] = size
            self._used += size
            self._lru[object_id] = time.monotonic()
            self.restored_bytes_total += size
            _mx().restores.inc()
            return True

    # -- lifecycle -----------------------------------------------------------
    def pin(self, object_id: ObjectID):
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._delete_locked(object_id)

    def _delete_locked(self, object_id: ObjectID):
        try:
            os.unlink(_obj_path(self.store_dir, object_id))
        except FileNotFoundError:
            pass
        was_spilled = self._spilled.pop(object_id, None) is not None
        if (was_spilled or object_id in self._ever_spilled) \
                and self._external is not None:
            self._ever_spilled.discard(object_id)
            try:
                self._external.delete(self._spill_key(object_id))
            except Exception:
                pass  # backend errors must not block the delete
        size = self._sizes.pop(object_id, 0)
        self._used -= size
        self._lru.pop(object_id, None)
        self._pinned.pop(object_id, None)

    def _ensure_space(self, size: int):
        with self._lock:
            self._ensure_space_locked(size)

    def _ensure_space_locked(self, size: int):
        if self._used + size <= self.capacity:
            return
        # SPILL-first when a spill target exists: nothing in this runtime
        # pins primary copies, and deleting the sole copy of a ray.put
        # object is unrecoverable data loss (puts have no lineage) — a
        # spilled object stays addressable and restores on access
        # (ray: local_object_manager.h:40).
        if self.spill_dir:
            for oid in list(self._lru.keys()):
                if self._used + size <= self.capacity:
                    break
                self._spill_locked(oid)
        # No spill target (or spilling failed): LRU-evict unpinned.
        for oid in list(self._lru.keys()):
            if self._used + size <= self.capacity:
                break
            if oid in self._pinned:
                continue
            self._delete_locked(oid)
        if self._used + size > self.capacity:
            raise ObjectStoreFullError(
                f"object of size {size} does not fit: used={self._used} "
                f"capacity={self.capacity} (all remaining objects pinned)"
            )

    def used_bytes(self) -> int:
        return self._used

    def spilled_stats(self):
        with self._lock:
            return {
                "spilled_objects": len(self._spilled),
                "spilled_bytes_total": self.spilled_bytes_total,
                "restored_bytes_total": self.restored_bytes_total,
            }

    def object_ids(self):
        with self._lock:
            return list(self._sizes.keys()) + list(self._spilled.keys())
