"""Shared-memory local object store (plasma analog).

The reference's plasma store (ray: src/ray/object_manager/plasma/store.h) is a
shm arena with create/seal/get/release and LRU eviction; workers map segments
read-only for zero-copy reads. Here each sealed object is a file in a
``/dev/shm``-backed session directory mapped with ``mmap``:

  layout:  [8B magic][8B metadata_len][8B data_len][metadata][data]

Writers create ``<id>.building`` then atomically rename to ``<id>.obj`` on
seal, so any process on the node can open + mmap a sealed object without
talking to a broker: the data plane is the kernel page cache, exactly one
copy per node. Accounting (capacity, pinning, LRU eviction) is done by the
raylet process that owns the store directory; readers in other processes only
open/mmap.

A C++ implementation with the same on-disk format can replace the
writer/accounting path without changing readers.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Iterable, Optional

from ray_tpu._private.ids import ObjectID

_MAGIC = b"RTPUOBJ1"
_HEADER = 24


class ObjectStoreFullError(Exception):
    pass


@dataclass
class ObjectBuffer:
    """A sealed object mapped into this process (zero-copy views)."""

    object_id: ObjectID
    metadata: bytes
    data: memoryview
    _mmap: mmap.mmap = None
    _file: object = None

    def release(self):
        if self._mmap is not None:
            try:
                self.data.release()
            except BufferError:
                pass
            self._mmap.close()
            self._file.close()
            self._mmap = None


def _obj_path(store_dir: str, object_id: ObjectID) -> str:
    return os.path.join(store_dir, object_id.hex() + ".obj")


def read_object(store_dir: str, object_id: ObjectID) -> Optional[ObjectBuffer]:
    """Open and mmap a sealed object. Returns None if absent. Any process."""
    path = _obj_path(store_dir, object_id)
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return None
    m = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    if m[:8] != _MAGIC:
        m.close()
        f.close()
        raise IOError(f"corrupt object {object_id}")
    meta_len = int.from_bytes(m[8:16], "little")
    data_len = int.from_bytes(m[16:24], "little")
    metadata = bytes(m[_HEADER : _HEADER + meta_len])
    data = memoryview(m)[_HEADER + meta_len : _HEADER + meta_len + data_len]
    return ObjectBuffer(object_id, metadata, data, _mmap=m, _file=f)


def object_exists(store_dir: str, object_id: ObjectID) -> bool:
    return os.path.exists(_obj_path(store_dir, object_id))


def write_object(
    store_dir: str,
    object_id: ObjectID,
    metadata: bytes,
    buffers: Iterable,
    total_data_len: int,
) -> int:
    """Create + seal an object from buffers. Returns bytes written.

    Safe from any process; accounting is reconciled by the owning store's
    directory scan. Writing an already-sealed id is a no-op (objects are
    immutable, so double-writes are benign).
    """
    final = _obj_path(store_dir, object_id)
    if os.path.exists(final):
        return 0
    from ray_tpu._private import native_store

    if native_store.available():
        return native_store.write_object(
            store_dir, object_id.hex(), metadata, buffers, total_data_len
        )
    tmp = final + f".building.{os.getpid()}"
    size = _HEADER + len(metadata) + total_data_len
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(len(metadata).to_bytes(8, "little"))
        f.write(total_data_len.to_bytes(8, "little"))
        f.write(metadata)
        for buf in buffers:
            f.write(buf)
    os.rename(tmp, final)
    return size


def make_local_store(store_dir: str, capacity_bytes: int):
    """Owner-side store factory: native C++ store (src/librtpu_store.so)
    when loadable, else the pure-Python implementation. Both share the
    same on-disk format, so mixed clusters interoperate."""
    from ray_tpu._private import native_store

    if native_store.available():
        return native_store.NativeLocalObjectStore(store_dir, capacity_bytes)
    return LocalObjectStore(store_dir, capacity_bytes)


class LocalObjectStore:
    """Owner-side store accounting: capacity, pinning, LRU eviction.

    Runs inside the raylet (one per node). Mirrors the reference's
    ObjectLifecycleManager + EvictionPolicy
    (ray: src/ray/object_manager/plasma/object_lifecycle_manager.h:101,
    eviction_policy.h:160).
    """

    def __init__(self, store_dir: str, capacity_bytes: int):
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        self._sizes: Dict[ObjectID, int] = {}
        self._lru: "OrderedDict[ObjectID, float]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._used = 0

    # -- write path ----------------------------------------------------------
    def put(self, object_id: ObjectID, metadata: bytes, buffers, total_data_len: int):
        size = _HEADER + len(metadata) + total_data_len
        self._ensure_space(size)
        written = write_object(self.store_dir, object_id, metadata, buffers, total_data_len)
        if written:
            with self._lock:
                self._sizes[object_id] = written
                self._used += written
                self._lru[object_id] = time.monotonic()

    def register_external(self, object_id: ObjectID):
        """Account for an object written directly by a worker process."""
        path = _obj_path(self.store_dir, object_id)
        try:
            size = os.path.getsize(path)
        except FileNotFoundError:
            return
        with self._lock:
            if object_id not in self._sizes:
                self._sizes[object_id] = size
                self._used += size
                self._lru[object_id] = time.monotonic()

    # -- read path -----------------------------------------------------------
    def get(self, object_id: ObjectID) -> Optional[ObjectBuffer]:
        buf = read_object(self.store_dir, object_id)
        if buf is not None:
            with self._lock:
                if object_id in self._lru:
                    self._lru.move_to_end(object_id)
        return buf

    def contains(self, object_id: ObjectID) -> bool:
        return object_exists(self.store_dir, object_id)

    # -- lifecycle -----------------------------------------------------------
    def pin(self, object_id: ObjectID):
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._delete_locked(object_id)

    def _delete_locked(self, object_id: ObjectID):
        try:
            os.unlink(_obj_path(self.store_dir, object_id))
        except FileNotFoundError:
            pass
        size = self._sizes.pop(object_id, 0)
        self._used -= size
        self._lru.pop(object_id, None)
        self._pinned.pop(object_id, None)

    def _ensure_space(self, size: int):
        with self._lock:
            if self._used + size <= self.capacity:
                return
            # LRU-evict unpinned objects until there is room.
            for oid in list(self._lru.keys()):
                if self._used + size <= self.capacity:
                    break
                if oid in self._pinned:
                    continue
                self._delete_locked(oid)
            if self._used + size > self.capacity:
                raise ObjectStoreFullError(
                    f"object of size {size} does not fit: used={self._used} "
                    f"capacity={self.capacity} (all remaining objects pinned)"
                )

    def used_bytes(self) -> int:
        return self._used

    def object_ids(self):
        with self._lock:
            return list(self._sizes.keys())
