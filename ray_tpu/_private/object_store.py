"""Shared-memory local object store (plasma analog).

The reference's plasma store (ray: src/ray/object_manager/plasma/store.h) is a
shm arena with create/seal/get/release and LRU eviction; workers map segments
read-only for zero-copy reads. The data plane here has two formats:

- **Slab arena** (default; slab_arena.py): workers lease pre-sized slab
  segments from their raylet, bump-allocate objects into the mmap'd
  segment and seal with an atomic header flip; readers resolve
  ``oid -> (segment, offset)`` through a shared-memory index and return
  memoryviews straight into the arena. No per-object file, no flock, no
  per-object syscalls on either side. Accounting is batched: the raylet
  charges capacity at slab granularity and workers self-report sealed
  entries asynchronously.
- **One file per object** (legacy + interop): ``<id>.obj`` files with
  ``[8B magic][8B metadata_len][8B data_len][metadata][data]``. Still the
  format for spill/restore and any process without a lease, so mixed
  clusters and external backends keep working; ``RAY_TPU_slab_arena=0``
  makes it the only data path again (including the native C++ writer).

Accounting (capacity, pinning, eviction/spill) is done by the raylet
process that owns the store directory; readers in other processes only
mmap. Lifetime is segment-granular in the arena: delete flips the entry
state word (live views keep their pages), and a segment file is unlinked
only when nothing live remains in it.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
import weakref
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

from ray_tpu._private import memview, slab_arena
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import ObjectID

_MAGIC = b"RTPUOBJ1"
_HEADER = 24

# negative-cache bound for external-backend probes (see _probe_missed)
_PROBE_MISSED_MAX = 100_000

# --- runtime metrics (metrics_core.py) ---------------------------------
# Built lazily; read_object/write_object run in every process (workers
# write returns directly, raylets serve pulls), so each process's
# registry sees its own share and the cluster scrape merges them.
_MX = None


class _StoreMetrics:
    __slots__ = ("put_lat", "put_bytes", "get_lat", "get_bytes",
                 "ext_hits", "ext_misses", "spills", "restores",
                 "slab_puts", "file_puts", "overshoot", "overshoot_cause",
                 "rx_assemblies", "punches", "punched_bytes")

    def __init__(self):
        from ray_tpu._private import metrics_core as mc

        reg = mc.registry()
        self.put_lat = reg.histogram(
            "object_store_put_latency_seconds",
            "Object create+seal latency", scale=mc.LATENCY).default
        self.put_bytes = reg.histogram(
            "object_store_put_bytes", "Object sizes written",
            scale=mc.SIZE).default
        self.get_lat = reg.histogram(
            "object_store_get_latency_seconds",
            "Object open+mmap latency", scale=mc.LATENCY).default
        self.get_bytes = reg.histogram(
            "object_store_get_bytes", "Object sizes mapped",
            scale=mc.SIZE).default
        self.ext_hits = reg.counter(
            "object_store_external_probe_hits_total",
            "External spill-backend existence probes that hit").default
        self.ext_misses = reg.counter(
            "object_store_external_probe_misses_total",
            "External spill-backend existence probes that missed").default
        self.spills = reg.counter(
            "object_store_spills_total", "Objects spilled out of shm").default
        self.restores = reg.counter(
            "object_store_restores_total",
            "Objects restored from the spill backend").default
        self.slab_puts = reg.counter(
            "object_store_slab_puts_total",
            "Objects sealed into leased slab segments").default
        self.file_puts = reg.counter(
            "object_store_file_puts_total",
            "Objects written as one-file .obj (fallback/interop)").default
        self.overshoot = reg.counter(
            "object_store_overshoot_bytes_total",
            "Bytes admitted past capacity (already-written externals "
            "and untracked restores)").default
        # cause-labeled twin of the total above: pressure verdicts name
        # register_external (fallback writes) vs untracked_restore
        # instead of pointing at a raw counter
        self.overshoot_cause = reg.counter(
            "object_store_overshoot_attributed_bytes_total",
            "Bytes admitted past capacity, by cause")
        # arena-to-arena transfer plane: cross-node receives assembled
        # straight into reserved slab entries (vs heap chunk buffers),
        # and hole-punch reclamation of dead ranges in live segments
        self.rx_assemblies = reg.counter(
            "object_store_slab_rx_assemblies_total",
            "Cross-node receives assembled directly into slab "
            "entries").default
        self.punches = reg.counter(
            "slab_punches_total",
            "Hole-punched dead ranges in live slab segments").default
        self.punched_bytes = reg.counter(
            "slab_punched_bytes_total",
            "Bytes hole-punched (physical pages returned) from dead "
            "ranges in live slab segments").default


def _mx() -> "_StoreMetrics":
    global _MX
    if _MX is None:
        _MX = _StoreMetrics()
    return _MX


class ObjectStoreFullError(Exception):
    pass


@dataclass
class ObjectBuffer:
    """A sealed object mapped into this process (zero-copy views).

    File-backed buffers own their mapping (+flock fd); slab-backed
    buffers alias the process's shared segment mapping and own nothing —
    ``release`` is then a no-op and ``seg_id`` names the segment."""

    object_id: ObjectID
    metadata: bytes
    data: memoryview
    _mmap: mmap.mmap = None
    _file: object = None
    seg_id: Optional[int] = None

    def release(self):
        if self._mmap is not None:
            try:
                self.data.release()
            except BufferError:
                pass
            try:
                self._mmap.close()
            except BufferError:
                # zero-copy slices of the data are still exported (e.g. a
                # chunk view queued on an rpc frame): the mapping closes
                # when the last view dies, and the weakref.finalize
                # attached at read time closes the flock fd with it
                self._mmap = None
                return
            if self._file is not None:
                self._file.close()  # finalize's second close is a no-op
            self._mmap = None


class SlabReservation:
    """One in-flight slab entry a cross-node transfer assembles into
    (receive-side slab assembly). The FULL entry header (real oid and
    lengths, known up front) is written at reserve time with state
    DEAD, so segment scans traverse an in-flight — or crashed —
    assembly like any dead entry and every entry sealed BEHIND it stays
    rescan-adoptable; chunks then pwrite straight into the segment file
    at their offsets (out-of-order safe, no heap staging), and
    ``seal()`` is a single atomic state-word flip DEAD→SEALED once
    every byte has arrived. An abandoned reservation simply stays DEAD
    (accounted as reclaimable dead bytes for the punch pass)."""

    __slots__ = ("_store", "object_id", "seg_id", "off", "meta_len",
                 "total_data_len", "entry_total", "_fd", "_done")

    def __init__(self, store, object_id: ObjectID, seg_id: int, off: int,
                 meta_len: int, total_data_len: int):
        self._store = store
        self.object_id = object_id
        self.seg_id = seg_id
        self.off = off
        self.meta_len = meta_len
        self.total_data_len = total_data_len
        self.entry_total = slab_arena.entry_size(meta_len, total_data_len)
        self._fd: Optional[int] = None
        self._done = False

    def write(self, data_off: int, buf) -> int:
        """Land one chunk at its data offset. Returns bytes written."""
        n = buf.nbytes if isinstance(buf, memoryview) else len(buf)
        if data_off < 0 or data_off + n > self.total_data_len:
            raise ValueError(
                f"chunk [{data_off}, {data_off + n}) outside reserved "
                f"data region of {self.total_data_len} bytes"
            )
        slab_arena.pwrite_all(
            self._fd, buf,
            self.off + slab_arena.HDR + self.meta_len + data_off)
        return n

    def seal(self) -> bool:
        """All bytes arrived: flip the state word DEAD→SEALED (the
        header body was written at reserve time), then ledger adoption
        + shared-index publish."""
        if self._done or self._fd is None:
            return False
        self._done = True
        try:
            os.pwrite(self._fd, slab_arena.STATE_SEALED, self.off)
        except OSError:
            self._done = False
            self.abandon()
            return False
        ok = self._store._commit_reservation(self)
        self._close()
        return ok

    def abandon(self):
        """Transfer failed/expired: the entry header already reads DEAD
        (written at reserve time) — account the range as reclaimable
        dead bytes. Idempotent."""
        if self._done:
            return
        self._done = True
        self._store._abandon_reservation(self)
        self._close()

    def _close(self):
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None


def _obj_path(store_dir: str, object_id: ObjectID) -> str:
    return os.path.join(store_dir, object_id.hex() + ".obj")


def read_object(store_dir: str, object_id: ObjectID) -> Optional[ObjectBuffer]:
    """Resolve + map a sealed object. Returns None if absent. Any process.

    Arena first: a shared-index hit validates the in-slab sealed header
    and returns views into the process's cached segment mapping —
    flock-free, no per-object syscalls. Legacy ``.obj`` files (spill
    restores, fallback writes, native-store output) keep the original
    open+flock path: readers hold a SHARED flock for the buffer's
    lifetime because the native free path's page-recycling pool takes a
    non-blocking EXCLUSIVE flock before rewriting pages; slab segments
    are never rewritten, which is why the arena path needs no lock."""
    t0 = time.perf_counter()
    hit = slab_arena.read(store_dir, object_id.binary())
    if hit is not None:
        metadata, data, seg_id = hit
        mx = _mx()
        mx.get_lat.record(time.perf_counter() - t0)
        mx.get_bytes.record(data.nbytes)
        return ObjectBuffer(object_id, metadata, data, seg_id=seg_id)
    return _read_object_file(store_dir, object_id, t0)


def _read_object_file(store_dir: str, object_id: ObjectID,
                      t0: float) -> Optional[ObjectBuffer]:
    import fcntl

    path = _obj_path(store_dir, object_id)
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return None
    try:
        fcntl.flock(f.fileno(), fcntl.LOCK_SH)
        if os.fstat(f.fileno()).st_ino != os.stat(path).st_ino:
            f.close()  # pooled/recycled between open and lock: gone
            return None
    except OSError:
        f.close()
        return None
    m = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    # the flock fd must outlive every exported view of the mapping, even
    # when release() can't close the mmap (BufferError): tie the fd's
    # close to the mapping's own collection
    weakref.finalize(m, f.close)
    if m[:8] != _MAGIC:
        m.close()
        f.close()
        raise IOError(f"corrupt object {object_id}")
    meta_len = int.from_bytes(m[8:16], "little")
    data_len = int.from_bytes(m[16:24], "little")
    metadata = bytes(m[_HEADER : _HEADER + meta_len])
    data = memoryview(m)[_HEADER + meta_len : _HEADER + meta_len + data_len]
    mx = _mx()
    mx.get_lat.record(time.perf_counter() - t0)
    mx.get_bytes.record(data_len)
    return ObjectBuffer(object_id, metadata, data, _mmap=m, _file=f)


def object_exists(store_dir: str, object_id: ObjectID) -> bool:
    return slab_arena.exists(store_dir, object_id.binary()) \
        or os.path.exists(_obj_path(store_dir, object_id))


def discard_local(store_dir: str, object_id: ObjectID) -> bool:
    """Drop the local copy whatever its backing: mark a slab entry dead
    (live views keep their pages) or unlink the ``.obj`` file. The
    test/chaos surface for simulating object loss."""
    dropped = slab_arena.discard(store_dir, object_id.binary())
    try:
        os.unlink(_obj_path(store_dir, object_id))
        dropped = True
    except FileNotFoundError:
        pass
    return dropped


def _write_object_file(store_dir: str, object_id: ObjectID, metadata: bytes,
                       buffers: Iterable, total_data_len: int) -> int:
    """One-file `.obj` write (no metrics; spill staging + fallback)."""
    final = _obj_path(store_dir, object_id)
    if os.path.exists(final):
        return 0
    from ray_tpu._private import native_store

    if native_store.available():
        return native_store.write_object(
            store_dir, object_id.hex(), metadata, buffers, total_data_len
        )
    tmp = final + f".building.{os.getpid()}"
    size = _HEADER + len(metadata) + total_data_len
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(len(metadata).to_bytes(8, "little"))
        f.write(total_data_len.to_bytes(8, "little"))
        f.write(metadata)
        for buf in buffers:
            f.write(buf)
    os.rename(tmp, final)
    return size


def write_object(
    store_dir: str,
    object_id: ObjectID,
    metadata: bytes,
    buffers: Iterable,
    total_data_len: int,
) -> int:
    """Create + seal a one-file object from buffers. Returns bytes written.

    Safe from any process; accounting is reconciled by the owning store's
    directory scan. Writing an already-sealed id is a no-op (objects are
    immutable, so double-writes are benign)."""
    t0 = time.perf_counter()
    written = _write_object_file(
        store_dir, object_id, metadata, buffers, total_data_len
    )
    if written:
        mx = _mx()
        mx.put_lat.record(time.perf_counter() - t0)
        mx.put_bytes.record(total_data_len)
        mx.file_puts.inc()
    return written


def make_local_store(store_dir: str, capacity_bytes: int,
                     spill_dir: Optional[str] = None):
    """Owner-side store factory. With the slab arena enabled (default)
    the Python store owns the node's data plane — the arena layout is
    python-first, and the native C++ writer stays gated behind
    ``RAY_TPU_slab_arena=0`` until it learns the slab format (the
    parity gate: both paths serve the same public store surface and the
    same test suite). Legacy mode picks the native store
    (src/librtpu_store.so) when loadable. ``spill_dir`` is a path OR a
    storage URI (ray: local_object_manager.h:40 + external_storage.py):
    file:///bare paths spill to disk; other schemes (s3://,
    test-registered) route through the pluggable driver."""
    if cfg.slab_arena:
        return LocalObjectStore(store_dir, capacity_bytes, spill_dir)
    from ray_tpu._private import native_store
    from ray_tpu._private.external_storage import is_local_spill_uri

    if native_store.available() and is_local_spill_uri(spill_dir):
        from urllib.parse import urlparse

        local = urlparse(spill_dir).path if (
            spill_dir and spill_dir.startswith("file://")
        ) else spill_dir
        return native_store.NativeLocalObjectStore(
            store_dir, capacity_bytes, local
        )
    return LocalObjectStore(store_dir, capacity_bytes, spill_dir,
                            arena=False)


class _Segment:
    """Owner-side record of one slab segment."""

    __slots__ = ("seg_id", "size", "leased_to", "last_access", "live",
                 "writer", "live_bytes", "dead", "reserved", "punched")

    def __init__(self, seg_id: int, size: int, leased_to: Optional[str]):
        self.seg_id = seg_id
        self.size = size  # accounted bytes (full lease, trimmed at seal)
        self.leased_to = leased_to  # client_id, "_local", or None=sealed
        self.last_access = time.monotonic()
        self.live: set = set()  # ObjectIDs resident in this segment
        # memory observatory (memview.py): the writing client survives
        # the seal (leased_to goes None) so per-client slab charge and
        # object ownership stay attributable, and deleted entries leave
        # their byte ranges behind — the input the hole-punch pass
        # (punch_holes) reclaims
        self.writer = leased_to
        self.live_bytes = 0
        self.dead: Dict[int, int] = {}  # entry offset -> entry bytes
        # in-flight receive-side assemblies (SlabReservation): an
        # unsealed entry a cross-node transfer is pwriting into — the
        # segment must not be unlinked or punched under it
        self.reserved = 0
        # hole-punched (tombstoned) ranges: range offset -> range bytes.
        # Retired from `dead` and the dead tallies at punch time; kept
        # so reconcile's rescan never re-counts a punched tombstone
        self.punched: Dict[int, int] = {}


class LocalObjectStore:
    """Owner-side store accounting: capacity, pinning, eviction, slabs.

    Runs inside the raylet (one per node). Mirrors the reference's
    ObjectLifecycleManager + EvictionPolicy
    (ray: src/ray/object_manager/plasma/object_lifecycle_manager.h:101,
    eviction_policy.h:160), with plasma's arena semantics: capacity is
    charged at slab-lease granularity, workers self-report sealed
    entries in batches, and reclamation is whole-segment.
    """

    def __init__(self, store_dir: str, capacity_bytes: int,
                 spill_dir: Optional[str] = None,
                 arena: Optional[bool] = None):
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        # URI-pluggable spill backend (ray parity: external_storage.py);
        # a bare path / file:// is the classic spill-to-disk
        from ray_tpu._private.external_storage import make_external_storage

        self._external = make_external_storage(spill_dir)
        self._spill_staging_root = self._resolve_spill_staging_root()
        self._sweep_stale_spill_staging()
        self._lock = threading.Lock()
        self._sizes: Dict[ObjectID, int] = {}  # file-backed objects
        self._lru: "OrderedDict[ObjectID, float]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._used = 0
        self._spilled: Dict[ObjectID, int] = {}  # oid -> size on disk
        # when each object left shm (memview: leak verdicts age-gate
        # against in-flight reports, so every lifecycle state needs an
        # age — arena rows carry their created ts in _slab_objs)
        self._spilled_at: Dict[ObjectID, float] = {}
        # restored-from-external objects whose backend copy still exists
        # (cleaned at delete); and oids whose one restart-recovery probe
        # already missed (never probe the backend again for them) —
        # bounded FIFO so an overflow evicts the oldest entries instead
        # of nuking the whole negative cache
        self._ever_spilled: set = set()
        self._probe_missed: "OrderedDict[ObjectID, None]" = OrderedDict()
        self.spilled_bytes_total = 0
        self.restored_bytes_total = 0
        self.overshoot_bytes_total = 0
        # overshoot attributed to its admission path (memview pressure
        # verdicts name the cause): register_external | untracked_restore
        self.overshoot_by_cause: Dict[str, int] = {}
        # --- slab arena (owner side) ----------------------------------
        self.arena_enabled = cfg.slab_arena if arena is None else arena
        self._segments: Dict[int, _Segment] = {}
        # oid -> (seg, off, len, created_monotonic)
        self._slab_objs: Dict[ObjectID, tuple] = {}
        # rolling arena occupancy (memview gauges: fragmentation ratio =
        # dead / (live + dead)); maintained at adopt/forget/unlink so a
        # metrics scrape never walks the ledger
        self._slab_live_bytes = 0
        self._slab_dead_bytes = 0
        # rolling hole-punch tallies (punch_holes): logical dead bytes
        # retired from the tallies above + physical bytes punched
        self._slab_punched_bytes = 0
        self._slab_punched_physical = 0
        self._punch_probe: Optional[bool] = None  # lazy support probe
        # deletes racing in-flight accounting reports (bounded FIFO —
        # frees of inline objects the store never saw land here too, and
        # must not pin memory or evict the cap into uselessness)
        self._pending_deletes: "OrderedDict[ObjectID, None]" = OrderedDict()
        self._next_seg = 0
        # segment recycling pool: all-dead segments parked (renamed) for
        # lease reuse — a steady put/free cadence writes into warm tmpfs
        # pages instead of faulting fresh zero pages per slab. Reuse is
        # gated on an EXCLUSIVE non-blocking flock (readers hold a SHARED
        # flock per cached segment mapping), so a segment some process
        # can still see is never rewritten. path -> (file_size, charged);
        # the charge stays on _used until the entry drains or is reused.
        self._pool: "OrderedDict[str, tuple]" = OrderedDict()
        self._pool_seq = 0
        self._pool_pinned_cache: tuple = (0.0, [])  # (ts, last probe)
        self._index = None
        self._local_writer = None
        if self.arena_enabled:
            os.makedirs(os.path.join(store_dir, slab_arena.SLAB_DIR),
                        exist_ok=True)
            self._index = slab_arena.SharedIndex(
                slab_arena.index_path(store_dir),
                slots=cfg.slab_index_slots, create=True,
            )
            self._local_writer = slab_arena.SlabWriter(store_dir)
            # serializes the put slow path (seal/lease/attach): two
            # concurrent refills would detach each other's fresh
            # "_local" segment, stranding its capacity charge
            self._local_put_lock = threading.Lock()
            with self._lock:
                self._rescan_segments_locked()

    # -- restart rescan ------------------------------------------------------
    def _rescan_segments_locked(self):
        """Adopt whatever a predecessor left in the slab dir: sealed
        entries become live objects again, torn tails (writer killed
        mid-put) are discarded by construction (scan stops at the first
        unsealed entry), and empty segments are unlinked."""
        slab_dir = os.path.join(self.store_dir, slab_arena.SLAB_DIR)
        try:
            names = os.listdir(slab_dir)
        except OSError:
            return
        for name in sorted(names):
            path = os.path.join(slab_dir, name)
            seg_id = slab_arena.segment_id_of(path)
            if seg_id is None:
                if name.startswith("pool_"):  # predecessor's recycle pool
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                continue
            self._next_seg = max(self._next_seg, seg_id + 1)
            seg = _Segment(seg_id, 0, leased_to=None)
            end = self._reconcile_segment_locked(seg)
            if not seg.live:
                # retire the dead-range tally with the file: this
                # segment never enters _segments, so its scan-counted
                # dead bytes would otherwise pin the gauge forever
                self._slab_dead_bytes -= sum(seg.dead.values())
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            seg.size = slab_arena.align_up(end)
            self._segments[seg_id] = seg
            self._used += seg.size

    # -- slab lease protocol (raylet-facing) ---------------------------------
    def lease_slab(self, client_id: str, nbytes: int,
                   seals=None) -> dict:
        """Grant one pre-sized slab segment to a writer (one RPC
        amortized over many puts). ``seals`` retires the caller's
        previous slab(s) in the same round trip."""
        if not self.arena_enabled:
            return {"ok": False}
        if isinstance(seals, dict):
            seals = [seals]
        nbytes = slab_arena.align_up(max(1, nbytes))
        with self._lock:
            for seal in seals or ():
                self._seal_segment_locked(
                    int(seal["seg_id"]), int(seal["used"]), client_id
                )
            try:
                self._ensure_space_locked(nbytes)
            except ObjectStoreFullError:
                return {"ok": False}
            seg_id, actual = self._create_segment_locked(client_id, nbytes)
        return {"ok": True, "seg_id": seg_id, "size": actual}

    _POOL_MIN_BYTES = 1 << 20  # pooling tiny segments isn't worth the rename

    def _create_segment_locked(self, client_id: str, size: int) -> tuple:
        """Create (or recycle) one segment; returns (seg_id, actual_size)
        — a reused pooled file may be larger than asked."""
        seg_id = self._next_seg
        self._next_seg += 1
        reused = self._reuse_pooled_locked(seg_id, size)
        if reused is None:
            slab_arena.create_segment(self.store_dir, seg_id, size)
            self._used += size
        else:
            size = reused
        self._segments[seg_id] = _Segment(seg_id, size, leased_to=client_id)
        return seg_id, size

    def _reuse_pooled_locked(self, seg_id: int, size: int) -> Optional[int]:
        """Adopt a pooled segment for a new lease when provably unmapped
        (exclusive flock) and big enough. Returns its file size, or
        None."""
        if not self._pool:
            return None
        import fcntl

        # our own reader cache may hold the SHARED flock of a pooled
        # (path-vanished) segment: release those first
        slab_arena.view(self.store_dir).sweep()
        for path, (fsize, charged) in list(self._pool.items()):
            if fsize < size:
                continue
            if self._used + (fsize - charged) > self.capacity:
                # adopting would re-charge the file's full length past
                # capacity — the lease's space check only approved
                # ``size``; an oversized pooled file must not sneak
                # unaccounted bytes in
                continue
            try:
                fd = os.open(path, os.O_RDWR)
            except OSError:
                self._pool.pop(path, None)
                continue
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)
                continue  # a reader still maps it: leave it pooled
            try:
                os.rename(path, slab_arena.segment_path(self.store_dir,
                                                        seg_id))
            except OSError:
                os.close(fd)
                self._pool.pop(path, None)
                continue
            os.close(fd)  # releases the probe flock
            self._pool.pop(path, None)
            self._used += fsize - charged  # re-charge at full file size
            return fsize

    def _seal_segment_locked(self, seg_id: int, used: int, client_id: str):
        seg = self._segments.get(seg_id)
        if seg is None or seg.leased_to != client_id:
            return
        # reconcile BEFORE trimming: sealed entries the writer never got
        # to report (lost notify, kill -9) are recovered from the slab
        # itself — the accounting protocol is advisory, the arena is
        # ground truth
        end = self._reconcile_segment_locked(seg)
        used = slab_arena.align_up(max(used, end))
        credit = seg.size - used
        if credit > 0:
            self._used -= credit
            seg.size = used
        seg.leased_to = None
        if not seg.live and not seg.reserved:
            self._unlink_segment_locked(seg)

    def _mark_dead_range_locked(self, seg: _Segment, off: int, total: int):
        """Account one dead entry range (idempotent: reconcile re-scans
        segments, and a range must count once — a punched range's
        covering tombstone scans as one big dead entry and must never
        re-enter the tallies it already left)."""
        if off in seg.dead or off in seg.punched:
            return
        seg.dead[off] = total
        self._slab_dead_bytes += total

    def _reconcile_segment_locked(self, seg: _Segment) -> int:
        """Scan a segment's sealed prefix into the ledger; returns the
        scan end offset. Idempotent with worker reports."""
        end = 0
        path = slab_arena.segment_path(self.store_dir, seg.seg_id)
        for oid_b, off, _ml, _dl, total, dead in slab_arena.scan_segment(path):
            end = off + total
            if dead:
                self._mark_dead_range_locked(seg, off, total)
                continue
            oid = ObjectID(oid_b)
            if oid in self._slab_objs:
                continue
            if oid in self._pending_deletes:
                # the free won the race against the writer's report (or
                # death): complete the delete — merely skipping would
                # leave the entry sealed and index-visible forever
                self._pending_deletes.pop(oid, None)
                slab_arena.mark_dead_at(self.store_dir, seg.seg_id, off)
                self._index.mark_dead(oid_b)
                self._mark_dead_range_locked(seg, off, total)
                continue
            seg.live.add(oid)
            seg.live_bytes += total
            self._slab_live_bytes += total
            self._slab_objs[oid] = (seg.seg_id, off, total, time.monotonic())
            self._index.insert(oid_b, seg.seg_id, off)
        return end

    def record_slab_objects(self, entries: Iterable[dict]) -> List[bytes]:
        """Batched accounting from writers: adopt reported entries into
        the ledger. Returns the oids that are NEW to this store (the
        caller registers their locations with the GCS in one batch)."""
        new: List[bytes] = []
        deletes: List[ObjectID] = []
        with self._lock:
            for e in entries:
                oid = ObjectID(bytes(e["o"]))
                seg = self._segments.get(int(e["s"]))
                if seg is None:
                    # segment already reclaimed (straggler report after a
                    # seal+unlink): the bytes are gone, nothing to adopt
                    continue
                if oid in self._slab_objs:
                    continue
                off, total = int(e["f"]), int(e["n"])
                if oid in self._pending_deletes:
                    # the free won the race: adopt the entry so the
                    # delete below can mark it dead, never resurrect it
                    self._pending_deletes.pop(oid, None)
                    seg.live.add(oid)
                    seg.live_bytes += total
                    self._slab_live_bytes += total
                    self._slab_objs[oid] = (seg.seg_id, off, total,
                                            time.monotonic(), e.get("c"))
                    deletes.append(oid)
                    continue
                seg.live.add(oid)
                seg.live_bytes += total
                self._slab_live_bytes += total
                seg.last_access = time.monotonic()
                # "c" = the owner's creation callsite riding the report:
                # persisted in the store ledger so a DEAD owner's leak
                # verdict still names the line that made the object
                self._slab_objs[oid] = (seg.seg_id, off, total,
                                        time.monotonic(), e.get("c"))
                self._probe_missed.pop(oid, None)
                new.append(oid.binary())
        for oid in deletes:
            self.delete(oid)
        return new

    def reclaim_client_slabs(self, client_id: str) -> List[bytes]:
        """A writer died: adopt the sealed prefix of every slab it still
        leased (unreported entries included; the torn mid-put tail, if
        any, is discarded by the scan) and make the segments evictable.
        Returns newly adopted oids for location registration.

        KV pages (``KVPG`` oid prefix, serve/llm/kv_cache.py) are the
        exception: a dead replica's KV cache is cache, not data — no
        process can ever reference those oids again, so adopting them
        would park them in the ledger until they aged into leak
        verdicts. They go straight to dead ranges (and the PUNCH_HOLE
        sweep) instead."""
        new: List[bytes] = []
        if not self.arena_enabled:
            return new
        kv_prefix = slab_arena.KV_PAGE_OID_PREFIX
        with self._lock:
            for seg in list(self._segments.values()):
                if seg.leased_to != client_id:
                    continue
                before = set(seg.live)
                end = self._reconcile_segment_locked(seg)
                for oid in [o for o in seg.live
                            if o.binary().startswith(kv_prefix)]:
                    self._delete_locked(oid)
                new.extend(o.binary() for o in seg.live - before)
                used = slab_arena.align_up(end)
                if seg.size > used:
                    self._used -= seg.size - used
                    seg.size = used
                seg.leased_to = None
                if not seg.live and not seg.reserved:
                    self._unlink_segment_locked(seg)
        return new

    def _unlink_segment_locked(self, seg: _Segment):
        """Retire an all-dead segment: park big ones in the recycling
        pool (warm pages for the next lease), unlink the rest."""
        path = slab_arena.segment_path(self.store_dir, seg.seg_id)
        self._segments.pop(seg.seg_id, None)
        # its dead ranges leave the arena with it (pooled files are
        # state-wiped; unlinked files are gone)
        self._slab_dead_bytes -= sum(seg.dead.values())
        self._slab_live_bytes -= seg.live_bytes
        seg.dead = {}
        seg.punched = {}
        seg.live_bytes = 0
        pool_cap = max(cfg.slab_size_bytes * 2, self.capacity // 4)
        pooled_bytes = sum(c for _f, c in self._pool.values())
        if seg.size >= self._POOL_MIN_BYTES \
                and pooled_bytes + seg.size <= pool_cap:
            try:
                fsize = os.path.getsize(path)  # full length, not the
                # seal-trimmed accounting size — reuse fits against this
                slab_arena.wipe_entry_states(path)
                self._pool_seq += 1
                pooled = os.path.join(
                    self.store_dir, slab_arena.SLAB_DIR,
                    f"pool_{self._pool_seq:08d}.slab",
                )
                os.rename(path, pooled)
                self._pool[pooled] = (fsize, seg.size)  # charge stays
                return
            except OSError:
                pass
        try:
            os.unlink(path)
        except OSError:
            pass
        self._used -= seg.size

    def _forget_slab_obj_locked(self, object_id: ObjectID,
                                mark_dead: bool = True):
        ent = self._slab_objs.pop(object_id, None)
        if ent is None:
            return
        seg_id, off, total = ent[:3]
        if mark_dead:
            slab_arena.mark_dead_at(self.store_dir, seg_id, off)
            self._index.mark_dead(object_id.binary())
        seg = self._segments.get(seg_id)
        if seg is not None:
            seg.live.discard(object_id)
            seg.live_bytes -= total
            self._slab_live_bytes -= total
            # discarded-behind-the-ledger entries (mark_dead=False) are
            # dead bytes in the segment all the same
            self._mark_dead_range_locked(seg, off, total)
            if not seg.live and seg.leased_to is None and not seg.reserved:
                self._unlink_segment_locked(seg)

    # -- write path ----------------------------------------------------------
    def _local_slab_alloc(self, entry_total: int, attempt):
        """Run one allocation ``attempt`` (a closure over the raylet's
        self-leased writer) through the seal/lease/attach slow path.
        ``attempt()`` returns its result or None when the current slab
        can't fit the entry; capacity exhaustion raises through
        ``_ensure_space_locked``. Shared by owner-local puts AND
        receive-side assembly reservations — the slab-writer plumbing
        the transfer plane rides."""
        ent = attempt()
        if ent is not None:
            return ent
        # a freshly attached segment can be consumed by the LOCK-FREE
        # fast path of a concurrent put before our retry lands, so loop;
        # true capacity exhaustion terminates via _ensure_space_locked's
        # raise
        with self._local_put_lock:
            for _ in range(8):
                ent = attempt()
                if ent is not None:
                    return ent
                with self._lock:
                    seal = self._local_writer.take_seal()
                    if seal:
                        self._seal_segment_locked(
                            seal["seg_id"], seal["used"], "_local"
                        )
                    size = max(entry_total,
                               min(cfg.slab_size_bytes,
                                   max(slab_arena.ALIGN,
                                       self.capacity // 8)))
                    self._ensure_space_locked(size)
                    seg_id, size = self._create_segment_locked(
                        "_local", size)
                self._local_writer.attach(seg_id, size)
            # the loop's last act was an attach: give the fresh segment
            # one final try before declaring failure
            return attempt()

    def put(self, object_id: ObjectID, metadata: bytes, buffers,
            total_data_len: int):
        """Owner-local put (pull/push receives, broadcasts): bump into the
        raylet's own slab — the raylet leases from itself, no RPC."""
        if not self.arena_enabled:
            return self._put_file(object_id, metadata, buffers,
                                  total_data_len)
        with self._lock:
            if object_id in self._slab_objs or object_id in self._sizes:
                return  # immutable: double-writes are benign
        t0 = time.perf_counter()
        entry_total = slab_arena.entry_size(len(metadata), total_data_len)
        ent = self._local_slab_alloc(
            entry_total,
            lambda: self._local_writer.try_put(
                object_id.binary(), metadata, buffers, total_data_len
            ),
        )
        if ent is None:
            raise ObjectStoreFullError(
                f"local slab put of {object_id.hex()} ({entry_total} bytes) "
                "kept losing freshly attached segments to concurrent puts"
            )
        self.record_slab_objects([ent])
        mx = _mx()
        mx.put_lat.record(time.perf_counter() - t0)
        mx.put_bytes.record(total_data_len)
        mx.slab_puts.inc()

    # -- receive-side slab assembly (arena-to-arena transfer plane) ----------
    def reserve(self, object_id: ObjectID, metadata: bytes,
                total_data_len: int) -> Optional["SlabReservation"]:
        """Reserve one in-flight slab entry for a cross-node transfer
        to assemble into: the real header goes down immediately with
        state DEAD (scans traverse it — entries sealed behind a crashed
        assembly stay rescan-adoptable), chunks pwrite straight into
        the segment file at their offsets (out-of-order safe), and
        ``seal()`` flips the state word DEAD→SEALED only when every
        byte has arrived — the same atomic-seal contract as a local
        put. Returns None when the transfer should fall back to heap
        assembly (arena off, store full, duplicate object)."""
        if not self.arena_enabled:
            return None
        with self._lock:
            if object_id in self._slab_objs or object_id in self._sizes:
                return None  # already resident: nothing to assemble
        entry_total = slab_arena.entry_size(len(metadata), total_data_len)

        def attempt():
            got = self._local_writer.try_reserve(entry_total)
            if got is None:
                return None
            seg_id, off = got
            # claim the range in the ledger ATOMICALLY with the bump: a
            # concurrent put's seal of this very segment must see
            # reserved>0 and keep the file alive under our pwrites; if
            # the seal already retired the segment (the take_seal beat
            # our try_reserve to the writer lock is impossible — the
            # writer detaches first — but a reserve that lost the store
            # lock to the seal is), treat it as slab-full and loop
            with self._lock:
                seg = self._segments.get(seg_id)
                if seg is None:
                    return None
                seg.reserved += 1
            return got

        try:
            got = self._local_slab_alloc(entry_total, attempt)
        except ObjectStoreFullError:
            return None  # transfer degrades to heap assembly + store.put
        if got is None:
            return None
        seg_id, off = got
        res = SlabReservation(self, object_id, seg_id, off,
                              len(metadata), total_data_len)
        try:
            fd = os.open(slab_arena.segment_path(self.store_dir, seg_id),
                         os.O_RDWR)
        except OSError:
            # no fd, no header written: the range stays a zero-state
            # (scan-stopping) torn entry — rare (open of a leased
            # segment's path), and the accounting still goes dead
            res.abandon()
            return None
        res._fd = fd
        try:
            # the REAL header goes down now, with state DEAD: oid and
            # lengths are known up front (the first chunk carries the
            # metadata), so a scan can traverse this in-flight entry —
            # a receiver crash strands nothing sealed behind it. Body
            # first, state word second: a crash between leaves a torn
            # entry, the old (scan-stopping) posture, in a microsecond
            # window instead of the whole transfer.
            hdr = slab_arena._pack_header(object_id.binary(),
                                          len(metadata), total_data_len)
            os.pwrite(fd, hdr[: slab_arena.HDR - 8], off + 8)
            os.pwrite(fd, slab_arena.STATE_DEAD, off)
            if metadata:
                slab_arena.pwrite_all(fd, metadata, off + slab_arena.HDR)
        except OSError:
            res.abandon()
            return None
        return res

    def _commit_reservation(self, res: "SlabReservation") -> bool:
        """All bytes arrived: seal (state-word flip), publish in the
        shared index, and adopt into the ledger — the receive-side twin
        of a worker's sealed-entry report."""
        ent = {"o": res.object_id.binary(), "s": res.seg_id,
               "f": res.off, "n": res.entry_total}
        # adopt FIRST, decrement the reservation count AFTER: while the
        # count still covers us, no racing abandon/evict can unlink (or
        # pool-recycle) the segment between the adoption and our check —
        # dropping the count first opened a window where a completed
        # transfer's segment vanished and the received bytes were lost
        self.record_slab_objects([ent])
        with self._lock:
            seg = self._segments.get(res.seg_id)
            if seg is not None:
                seg.reserved = max(0, seg.reserved - 1)
            cur = self._slab_objs.get(res.object_id)
            ours = (cur is not None and cur[0] == res.seg_id
                    and cur[1] == res.off)
            if not ours:
                # a racing session/put sealed this object first (or the
                # free raced the adoption): OUR sealed entry is
                # unreachable by the ledger — tombstone it dead so its
                # bytes are reclaimable instead of leaking until the
                # segment dies
                if seg is not None and res._fd is not None:
                    try:
                        os.pwrite(res._fd, slab_arena.STATE_DEAD, res.off)
                    except OSError:
                        pass
                    self._mark_dead_range_locked(seg, res.off,
                                                 res.entry_total)
                if seg is not None and not seg.live \
                        and seg.leased_to is None and not seg.reserved:
                    self._unlink_segment_locked(seg)
                return False
            if seg is not None:
                # a slab-seal reconcile may have scanned our in-flight
                # (DEAD-state) entry into the dead tallies: it is live
                # now — un-count it or the range reads punchable forever
                stale = seg.dead.pop(res.off, None)
                if stale:
                    self._slab_dead_bytes -= stale
        self._index.insert(res.object_id.binary(), res.seg_id, res.off)
        mx = _mx()
        mx.put_bytes.record(res.total_data_len)
        mx.slab_puts.inc()
        mx.rx_assemblies.inc()
        return True

    def _abandon_reservation(self, res: "SlabReservation"):
        """The transfer died (sender gone, session expired, chunk
        failure): the entry header already reads DEAD (written at
        reserve time, so scans hop it either way) — account the range
        as dead bytes for the punch pass like any other dead entry."""
        with self._lock:
            seg = self._segments.get(res.seg_id)
            if seg is None:
                return
            seg.reserved = max(0, seg.reserved - 1)
            self._mark_dead_range_locked(seg, res.off, res.entry_total)
            if not seg.live and seg.leased_to is None and not seg.reserved:
                self._unlink_segment_locked(seg)

    def _put_file(self, object_id: ObjectID, metadata: bytes, buffers,
                  total_data_len: int):
        size = _HEADER + len(metadata) + total_data_len
        self._ensure_space(size)
        written = write_object(self.store_dir, object_id, metadata, buffers,
                               total_data_len)
        if written:
            with self._lock:
                self._sizes[object_id] = written
                self._used += written
                self._lru[object_id] = time.monotonic()
                # the id exists now: a previously-cached miss must not
                # mask a later spill-restore of this object
                self._probe_missed.pop(object_id, None)

    def register_external(self, object_id: ObjectID):
        """Account for a one-file object written directly by another
        process (lease-less fallback writes, restores) — capacity is
        enforced here too (spilling older objects to make room; the new
        object is already on shm, so the budget is made around it)."""
        path = _obj_path(self.store_dir, object_id)
        try:
            size = os.path.getsize(path)
        except FileNotFoundError:
            return
        with self._lock:
            if object_id in self._pending_deletes:
                # the owner already freed this object while its
                # registration was in flight: complete the delete
                self._pending_deletes.pop(object_id, None)
                try:
                    os.unlink(path)
                except OSError:
                    pass
                return
            self._probe_missed.pop(object_id, None)
            if object_id not in self._sizes:
                try:
                    self._ensure_space_locked(size)
                except ObjectStoreFullError:
                    # already written: track the overshoot honestly
                    self._count_overshoot_locked(size, "register_external")
                self._sizes[object_id] = size
                self._used += size
                self._lru[object_id] = time.monotonic()

    def _count_overshoot_locked(self, size: int, cause: str):
        over = min(size, max(0, self._used + size - self.capacity))
        if over > 0:
            self.overshoot_bytes_total += over
            self.overshoot_by_cause[cause] = \
                self.overshoot_by_cause.get(cause, 0) + over
            mx = _mx()
            mx.overshoot.inc(over)
            mx.overshoot_cause.labels(cause=cause).inc(over)

    # -- read path -----------------------------------------------------------
    def _slab_read(self, object_id: ObjectID) -> Optional[ObjectBuffer]:
        t0 = time.perf_counter()
        with self._lock:
            ent = self._slab_objs.get(object_id)
        if ent is not None:
            seg_id, off = ent[0], ent[1]
            got = slab_arena.read_at(self.store_dir, seg_id, off,
                                     object_id.binary())
            if got is not None:
                metadata, data = got
                with self._lock:
                    seg = self._segments.get(seg_id)
                    if seg is not None:
                        seg.last_access = time.monotonic()
                # index repair: a lost insert (slot race) must not force
                # every reader onto the RPC fallback forever
                if self._index.lookup(object_id.binary()) is None:
                    self._index.insert(object_id.binary(), seg_id, off)
                return self._record_get(
                    ObjectBuffer(object_id, metadata, data, seg_id=seg_id),
                    t0,
                )
            # discarded/torn behind the ledger: drop the record
            with self._lock:
                self._forget_slab_obj_locked(object_id, mark_dead=False)
            return None
        # not in the ledger yet (report in flight): the shared index is
        # the writer's synchronous publication — trust it
        hit = slab_arena.read(self.store_dir, object_id.binary())
        if hit is not None:
            metadata, data, seg_id = hit
            return self._record_get(
                ObjectBuffer(object_id, metadata, data, seg_id=seg_id), t0
            )
        return None

    @staticmethod
    def _record_get(buf: ObjectBuffer, t0: float) -> ObjectBuffer:
        # raylets serve pulls from here: slab reads must show in the
        # get histograms just like the file path's do
        mx = _mx()
        mx.get_lat.record(time.perf_counter() - t0)
        mx.get_bytes.record(buf.data.nbytes)
        return buf

    def get(self, object_id: ObjectID) -> Optional[ObjectBuffer]:
        if self.arena_enabled:
            buf = self._slab_read(object_id)
            if buf is not None:
                return buf
        buf = _read_object_file(self.store_dir, object_id,
                                time.perf_counter())
        if buf is None and (object_id in self._spilled
                            or self._external is not None):
            # second disjunct = restart recovery: a fresh raylet's ledger
            # doesn't know what its predecessor spilled externally
            if self.restore_if_spilled(object_id):
                buf = _read_object_file(self.store_dir, object_id,
                                        time.perf_counter())
        if buf is not None:
            with self._lock:
                if object_id in self._lru:
                    self._lru.move_to_end(object_id)
        return buf

    def contains(self, object_id: ObjectID) -> bool:
        if self.arena_enabled:
            with self._lock:
                ent = self._slab_objs.get(object_id)
            if ent is not None:
                state = slab_arena.state_at(self.store_dir, ent[0], ent[1],
                                            object_id.binary())
                if state == slab_arena.STATE_SEALED:
                    return True
                with self._lock:
                    self._forget_slab_obj_locked(object_id, mark_dead=False)
            elif slab_arena.exists(self.store_dir, object_id.binary()):
                return True  # unreported writer object via the shared index
        if os.path.exists(_obj_path(self.store_dir, object_id)) \
                or object_id in self._spilled:
            return True
        if self._external is None or object_id in self._probe_missed:
            return False
        try:
            found = self._external.exists(self._spill_key(object_id))
        except Exception:
            found = False
        (_mx().ext_hits if found else _mx().ext_misses).inc()
        if not found:
            # at most ONE external round trip per unseen id (the restore
            # path's contract): a routine containment check for an object
            # living on another node must not pay a backend probe forever.
            # Cleared when the object actually lands here (put /
            # register_external).
            with self._lock:
                self._probe_missed_add_locked(object_id)
        return found

    def _probe_missed_add_locked(self, object_id: ObjectID):
        self._probe_missed[object_id] = None
        self._probe_missed.move_to_end(object_id)
        while len(self._probe_missed) > _PROBE_MISSED_MAX:
            self._probe_missed.popitem(last=False)  # bounded FIFO eviction

    # -- spilling (ray: local_object_manager.h SpillObjects/restore) ---------
    @staticmethod
    def _spill_key(object_id: ObjectID) -> str:
        # deterministic, node-independent: a restarted raylet (new node
        # id) can restore a predecessor's externally-spilled objects
        return object_id.hex() + ".obj"

    def _resolve_spill_staging_root(self) -> str:
        """Parent dir for mid-spill ``.obj`` staging. Spilling runs
        exactly when shm is over capacity, and on many hosts /tmp is
        itself tmpfs — staging there would double RAM-backed usage per
        object while memory is the resource being reclaimed. Prefer the
        spill destination's own filesystem when it is local;
        ``spill_staging_dir`` overrides, system temp is the last resort
        (non-local backends with no override)."""
        import tempfile

        from ray_tpu._private.external_storage import FileSystemStorage

        if cfg.spill_staging_dir:
            return cfg.spill_staging_dir
        if isinstance(self._external, FileSystemStorage):
            return self._external.root
        return tempfile.gettempdir()

    def _staging_dir_name(self) -> str:
        # host-qualified: a file:// spill root may be a shared NFS/GCS
        # mount, and pid liveness is only checkable on the owning host
        return f"rtpu_spill_stage_{os.uname().nodename}_{os.getpid()}"

    def _sweep_stale_spill_staging(self):
        """Remove rtpu_spill_stage_<host>_<pid> dirs stranded by a
        raylet that died mid-spill. Only THIS host's dirs are judged —
        on a shared spill mount another node's pid space is opaque, and
        sweeping its live staging would fail its in-flight spills."""
        import shutil

        try:
            names = os.listdir(self._spill_staging_root)
        except OSError:
            return
        host = os.uname().nodename
        for name in names:
            if not name.startswith("rtpu_spill_stage_"):
                continue
            owner, _, pid_s = name[len("rtpu_spill_stage_"):].rpartition("_")
            try:
                pid = int(pid_s)
            except ValueError:
                continue  # not our naming scheme: leave it
            if owner != host:
                continue
            if pid != os.getpid():
                try:
                    os.kill(pid, 0)
                    continue  # owner still alive — not ours to sweep
                except ProcessLookupError:
                    pass
                except OSError:
                    continue  # exists under another uid: leave it
            shutil.rmtree(
                os.path.join(self._spill_staging_root, name),
                ignore_errors=True,
            )

    def _spill_locked(self, object_id: ObjectID) -> bool:
        """Move one file-backed object from shm to the external backend;
        the object stays addressable and is restored on access. Pin
        counts survive: a spilled primary copy is still the primary."""
        src = _obj_path(self.store_dir, object_id)
        size = self._sizes.get(object_id, 0)
        t0 = time.perf_counter()
        try:
            self._external.spill(self._spill_key(object_id), src)
            os.unlink(src)
        except Exception:
            return False  # backend errors (boto, plugin) degrade to no-spill
        self._sizes.pop(object_id, None)
        self._lru.pop(object_id, None)
        self._used -= size
        self._spilled[object_id] = size
        self._spilled_at[object_id] = time.monotonic()
        self.spilled_bytes_total += size
        _mx().spills.inc()
        memview.record_flow("spill", size, time.perf_counter() - t0,
                            "file", object_id.hex())
        return True

    def _spill_slab_object_locked(self, object_id: ObjectID) -> bool:
        """Stage one slab entry out as a `.obj` file (the spill/interop
        format) and hand it to the backend; the slab entry is then marked
        dead. Restore brings it back file-backed."""
        ent = self._slab_objs.get(object_id)
        if ent is None:
            return False
        seg_id, off = ent[0], ent[1]
        t0 = time.perf_counter()
        got = slab_arena.read_at(self.store_dir, seg_id, off,
                                 object_id.binary())
        if got is None:  # discarded behind the ledger
            self._forget_slab_obj_locked(object_id, mark_dead=False)
            return False
        metadata, data = got
        # stage outside the shm store_dir, on the spill destination's
        # filesystem when local (see _resolve_spill_staging_root):
        # backends only read local_path, so any filesystem works, but a
        # tmpfs staging copy would consume the memory being reclaimed
        staging = os.path.join(self._spill_staging_root,
                               self._staging_dir_name())
        os.makedirs(staging, exist_ok=True)
        src = _obj_path(staging, object_id)
        try:
            size = _write_object_file(staging, object_id, metadata,
                                      [data], data.nbytes) \
                or os.path.getsize(src)
            # same-filesystem backends adopt the staged file by rename
            # (one disk write per object, not two); others copy
            mover = getattr(self._external, "spill_move", None)
            if mover is None or not mover(self._spill_key(object_id), src):
                self._external.spill(self._spill_key(object_id), src)
        except Exception:
            self._drop_staged_locked(staging, src)
            return False
        finally:
            data.release()
        self._drop_staged_locked(staging, src)
        self._forget_slab_obj_locked(object_id)
        self._spilled[object_id] = size
        self._spilled_at[object_id] = time.monotonic()
        self.spilled_bytes_total += size
        _mx().spills.inc()
        # arena path: bytes left straight from the slab mapping (the
        # one disk write is the staged interop file)
        memview.record_flow("spill", size, time.perf_counter() - t0,
                            "arena", object_id.hex())
        return True

    @staticmethod
    def _drop_staged_locked(staging: str, src: str):
        """Remove a staged spill copy and its per-pid dir (when empty) —
        a FileSystemStorage backend shares its root with the staging
        parent, and lingering dirs read as stranded spill state."""
        try:
            os.unlink(src)
        except OSError:
            pass
        try:
            os.rmdir(staging)
        except OSError:
            pass  # another spill in flight, or already gone

    def _spill_segment_locked(self, seg: _Segment) -> bool:
        progressed = False
        for oid in list(seg.live):
            progressed |= self._spill_slab_object_locked(oid)
        return progressed

    def restore_if_spilled(self, object_id: ObjectID) -> bool:
        """Bring a spilled object back into shm (ray:
        spilled_object_reader.h — we restore whole objects, file-backed).

        The EXTERNAL copy is deliberately left in place: objects are
        immutable, so with a shared backend (s3) another raylet may
        restore the same key concurrently — deleting on restore would
        destroy a peer's only spilled copy and strand its ledger. The
        external copy is cleaned when the OBJECT is deleted (refcount
        zero), tracked via _ever_spilled."""
        with self._lock:
            size = self._spilled.get(object_id)
            untracked = size is None
            if untracked:
                if self._external is None:
                    return False
                # restart-recovery probe: at most ONE external lookup per
                # unseen oid — a routine miss for an object living on
                # another node must not pay a backend round trip forever
                if object_id in self._probe_missed:
                    return False
            else:
                try:
                    self._ensure_space_locked(size)
                except ObjectStoreFullError:
                    return False
            dst = _obj_path(self.store_dir, object_id)
            t0 = time.perf_counter()
            try:
                ok = self._external.restore(
                    self._spill_key(object_id), dst
                )
            except Exception:
                ok = False  # backend errors (boto, plugin) degrade to miss
            if not ok:
                if untracked:
                    self._probe_missed_add_locked(object_id)
                return False
            if untracked:
                # a predecessor raylet spilled this object; its size
                # wasn't in our (fresh) ledger — the file is already on
                # shm, so a full store tracks the overshoot honestly
                try:
                    size = os.path.getsize(dst)
                except OSError:
                    size = 0
                try:
                    self._ensure_space_locked(size)
                except ObjectStoreFullError:
                    self._count_overshoot_locked(size, "untracked_restore")
            self._spilled.pop(object_id, None)
            self._spilled_at.pop(object_id, None)
            self._ever_spilled.add(object_id)
            self._sizes[object_id] = size
            self._used += size
            self._lru[object_id] = time.monotonic()
            self.restored_bytes_total += size
            _mx().restores.inc()
            memview.record_flow("restore", size,
                                time.perf_counter() - t0, "file",
                                object_id.hex())
            return True

    # -- lifecycle -----------------------------------------------------------
    def pin(self, object_id: ObjectID):
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._delete_locked(object_id)

    def delete_many(self, object_ids: Iterable[ObjectID]):
        """Batched delete: one lock acquisition per free burst (owners
        tick-batch frees; a 10k-object teardown should not pay 10k lock
        round trips on the raylet loop)."""
        with self._lock:
            for oid in object_ids:
                self._delete_locked(oid)

    def forget(self, object_id: ObjectID):
        """Drop a LOST object's records WITHOUT the pending-delete
        tombstone. A loss report is not a free: lineage reconstruction
        will re-put this very oid, and a tombstone would kill the fresh
        copy the moment its accounting report lands."""
        with self._lock:
            self._delete_locked(object_id, tombstone=False)

    def _delete_locked(self, object_id: ObjectID, tombstone: bool = True):
        # This is the raylet's hottest non-data path: owners free EVERY
        # owned object through it, including inline values the store
        # never saw — the unknown-oid case must stay a few dict misses.
        size = self._sizes.pop(object_id, 0)
        known_file = size > 0
        if self.arena_enabled:
            if object_id in self._slab_objs:
                self._forget_slab_obj_locked(object_id)
            elif tombstone and not known_file \
                    and object_id not in self._spilled:
                # a free can race the writer's in-flight accounting
                # report: remember it so record_slab_objects completes
                # the delete instead of resurrecting the object. No
                # index probe here — frees of inline objects vastly
                # outnumber real races, and a per-free probe is raylet
                # CPU stolen from the data path on teardown bursts.
                self._pending_deletes[object_id] = None
                while len(self._pending_deletes) > 10_000:
                    self._pending_deletes.popitem(last=False)
        # No filesystem touch for oids the ledger doesn't know (the
        # common case: freed inline/slab objects have no .obj file, and
        # a stat costs microseconds under a sandboxed kernel). The one
        # race — a fallback .obj write whose register_put is still in
        # flight — is closed in register_external via _pending_deletes.
        if known_file or not self.arena_enabled:
            try:
                os.unlink(_obj_path(self.store_dir, object_id))
            except FileNotFoundError:
                pass
        was_spilled = self._spilled.pop(object_id, None) is not None
        self._spilled_at.pop(object_id, None)
        if (was_spilled or object_id in self._ever_spilled) \
                and self._external is not None:
            self._ever_spilled.discard(object_id)
            try:
                self._external.delete(self._spill_key(object_id))
            except Exception:
                pass  # backend errors must not block the delete
        self._used -= size
        self._lru.pop(object_id, None)
        self._pinned.pop(object_id, None)

    def _ensure_space(self, size: int):
        with self._lock:
            self._ensure_space_locked(size)

    def _fits_locked(self, size: int) -> bool:
        return self._used + size <= self.capacity

    def _ensure_space_locked(self, size: int):
        if self._fits_locked(size):
            return
        # recycling pool first: pooled segments are instantly reclaimable
        self._drain_pool_locked(size)
        if self._fits_locked(size):
            return
        # SPILL-first when a spill target exists: nothing in this runtime
        # pins primary copies, and deleting the sole copy of a ray.put
        # object is unrecoverable data loss (puts have no lineage) — a
        # spilled object stays addressable and restores on access
        # (ray: local_object_manager.h:40).
        if self.spill_dir:
            for oid in list(self._lru.keys()):
                if self._fits_locked(size):
                    break
                self._spill_locked(oid)
            # then whole segments, coldest first; leased slabs are off
            # limits (their writers are mid-put in them)
            for seg in self._sealed_segments_lru_locked():
                if self._fits_locked(size):
                    break
                self._spill_segment_locked(seg)
        # No spill target (or spilling failed): LRU-evict unpinned.
        for oid in list(self._lru.keys()):
            if self._fits_locked(size):
                break
            if oid in self._pinned:
                continue
            self._delete_locked(oid)
        for seg in self._sealed_segments_lru_locked():
            if self._fits_locked(size):
                break
            if any(oid in self._pinned for oid in seg.live):
                continue
            for oid in list(seg.live):
                self._delete_locked(oid)
        # segments spilled/evicted above re-park in the pool with their
        # charge intact — drain again before declaring the store full
        self._drain_pool_locked(size)
        if not self._fits_locked(size):
            raise ObjectStoreFullError(
                f"object of size {size} does not fit: used={self._used} "
                f"capacity={self.capacity} (remaining objects pinned or "
                f"in leased slabs)"
            )

    def _drain_pool_locked(self, size: int):
        """Unlink pooled (all-dead, renamed) segments oldest-first until
        ``size`` fits; their retained charge comes off _used."""
        while self._pool and not self._fits_locked(size):
            path, (_fsize, charged) = self._pool.popitem(last=False)
            try:
                os.unlink(path)
            except OSError:
                pass
            self._used -= charged

    def _sealed_segments_lru_locked(self) -> List[_Segment]:
        return sorted(
            (s for s in self._segments.values() if s.leased_to is None),
            key=lambda s: s.last_access,
        )

    def used_bytes(self) -> int:
        return self._used

    def spilled_stats(self):
        with self._lock:
            return self._spilled_stats_locked()

    def _spilled_stats_locked(self):
        return {
            "spilled_objects": len(self._spilled),
            "spilled_bytes_total": self.spilled_bytes_total,
            "restored_bytes_total": self.restored_bytes_total,
            "overshoot_bytes_total": self.overshoot_bytes_total,
            "overshoot_by_cause": dict(self.overshoot_by_cause),
            "slab_segments": len(self._segments),
            "slab_objects": len(self._slab_objs),
        }

    def object_ids(self):
        with self._lock:
            return list(self._sizes.keys()) + list(self._slab_objs.keys()) \
                + list(self._spilled.keys())

    # -- hole-punch reclamation (arena-to-arena transfer plane) --------------
    def punch_supported(self) -> bool:
        """One-shot probe: can this store_dir's filesystem hole-punch?
        (tmpfs can since Linux 3.5; sandboxed kernels may not)."""
        if self._punch_probe is None:
            probe = os.path.join(self.store_dir,
                                 f".punch_probe.{os.getpid()}")
            try:
                fd = os.open(probe, os.O_RDWR | os.O_CREAT, 0o600)
                try:
                    os.ftruncate(fd, slab_arena.PAGE * 2)
                    self._punch_probe = slab_arena.punch_range(
                        fd, 0, slab_arena.PAGE)
                finally:
                    os.close(fd)
                    os.unlink(probe)
            except OSError:
                self._punch_probe = False
        return bool(self._punch_probe)

    def punch_holes(self, min_fragmentation: Optional[float] = None,
                    min_bytes: Optional[int] = None) -> dict:
        """Reclaim physical pages from dead entry ranges inside LIVE
        segments via ``fallocate(PUNCH_HOLE | KEEP_SIZE)`` — memory
        comes back without waiting for whole-segment emptiness.

        Per candidate segment (sealed, fragmentation >= threshold, no
        in-flight reservations): drop our own cached read mapping, take
        a non-blocking EXCLUSIVE flock (readers hold SHARED flocks per
        cached mapping — a pinned segment is SKIPPED, because a reader's
        live view may alias entries deleted after the view was taken),
        write one covering DEAD tombstone per coalesced range (so scans
        hop the zeroed interior), punch the page-aligned interior
        (KEEP_SIZE: the file size and every future mapping stay intact),
        and retire the range from the dead-byte tallies. Runs on an
        executor thread off the raylet loop."""
        import fcntl

        out = {"punched_ranges": 0, "punched_bytes": 0,
               "dead_bytes_retired": 0, "skipped_pinned": 0,
               "segments": 0}
        if not self.arena_enabled or not self.punch_supported():
            return out
        min_frag = (cfg.slab_punch_min_fragmentation
                    if min_fragmentation is None else min_fragmentation)
        min_b = cfg.slab_punch_min_bytes if min_bytes is None else min_bytes
        with self._lock:
            candidates = []
            for seg in self._segments.values():
                if seg.leased_to is not None or seg.reserved:
                    continue  # a writer/assembly is mid-flight in it
                dead = sum(seg.dead.values())
                denom = seg.live_bytes + dead
                if dead >= min_b and denom and dead / denom >= min_frag:
                    candidates.append(seg.seg_id)
        t0 = time.perf_counter()
        broken = False
        for seg_id in candidates:
            if broken:
                break
            # our own reader cache holds a SHARED flock per cached
            # mapping: release ours first (outside the store lock; view
            # has its own) so the probe reports FOREIGN readers only. A
            # refusal means our own exported zero-copy views pin it.
            if not slab_arena.view(self.store_dir).drop_segment(seg_id):
                out["skipped_pinned"] += 1
                continue
            with self._lock:
                seg = self._segments.get(seg_id)
                if seg is None or seg.leased_to is not None or seg.reserved:
                    continue
                path = slab_arena.segment_path(self.store_dir, seg_id)
                try:
                    fd = os.open(path, os.O_RDWR)
                except OSError:
                    continue
                try:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    except OSError:
                        out["skipped_pinned"] += 1
                        continue
                    progressed = False
                    # coalesce over dead AND already-punched ranges: a
                    # sub-page range adjacent to a punched neighbor can
                    # only reclaim by merging across it (re-punching the
                    # neighbor's pages is a cheap no-op); ranges already
                    # punched in full are skipped outright
                    for off, length in memview.coalesce_ranges(
                            list(seg.dead.items())
                            + list(seg.punched.items())):
                        if seg.punched.get(off) == length:
                            continue  # fully punched already
                        span = slab_arena.punch_span(off, length)
                        if span is None:
                            continue  # sub-page: wait for a neighbor
                        if not slab_arena.write_dead_tombstone(
                                fd, off, length):
                            continue
                        if not slab_arena.punch_range(fd, *span):
                            broken = True  # unsupported/failed: stop pass
                            break
                        freed = 0
                        for o in [o for o in seg.dead
                                  if off <= o < off + length]:
                            freed += seg.dead.pop(o)
                        # merged-in previously-punched subranges: their
                        # pages are already holes — count only the NEW
                        # physical yield or repeated adjacent frees next
                        # to a big punched range inflate the counters
                        prev_phys = 0
                        for o in [o for o in seg.punched
                                  if off <= o < off + length]:
                            ps = slab_arena.punch_span(o,
                                                       seg.punched.pop(o))
                            if ps:
                                prev_phys += ps[1]
                        new_phys = max(0, span[1] - prev_phys)
                        self._slab_dead_bytes -= freed
                        self._slab_punched_bytes += freed
                        self._slab_punched_physical += new_phys
                        seg.punched[off] = length
                        out["punched_ranges"] += 1
                        out["punched_bytes"] += new_phys
                        out["dead_bytes_retired"] += freed
                        progressed = True
                    if progressed:
                        out["segments"] += 1
                finally:
                    os.close(fd)  # releases the probe flock
        if out["punched_ranges"]:
            mx = _mx()
            mx.punches.inc(out["punched_ranges"])
            mx.punched_bytes.inc(out["punched_bytes"])
            memview.record_flow("punch", out["dead_bytes_retired"],
                                time.perf_counter() - t0, "arena")
        return out

    # -- memory observatory (memview.py) -------------------------------------
    def arena_dead_bytes(self) -> int:
        return self._slab_dead_bytes

    def arena_live_bytes(self) -> int:
        return self._slab_live_bytes

    def arena_punched_bytes(self) -> int:
        """Cumulative dead bytes retired by the hole-punch pass."""
        return self._slab_punched_bytes

    def arena_fragmentation(self) -> float:
        """dead / (live + dead) resident slab bytes — the share a
        hole-punch pass could reclaim from live segments."""
        total = self._slab_dead_bytes + self._slab_live_bytes
        return self._slab_dead_bytes / total if total else 0.0

    def pool_pinned(self, max_age_s: float = 0.0) -> List[dict]:
        """Recycling-pool segments a reader's SHARED flock keeps alive
        (an EXCLUSIVE non-blocking probe fails): previously invisible —
        a stuck zero-copy view pinned pages forever with nothing to
        blame. Reports the pinning pid(s) from /proc/locks.

        The probe runs UNDER the store lock so it serializes with
        ``_reuse_pooled_locked``'s identical EX probe — two transient
        exclusive locks racing would make the recycler skip a reusable
        segment and this report name the raylet's own pid as a phantom
        pinner. ``max_age_s`` serves a recent cached result instead of
        re-probing (the per-scrape gauge path; introspection and tests
        pass 0 for ground truth)."""
        import fcntl

        if max_age_s > 0.0:
            ts, cached = self._pool_pinned_cache
            if time.monotonic() - ts < max_age_s:
                return cached
        # our own reader cache legitimately holds SHARED flocks of
        # pooled (path-vanished) segments: release those first so the
        # probe reports FOREIGN pins, not our own cache. Outside the
        # store lock (the view has its own; lock order store->view is
        # the established one — see _reuse_pooled_locked).
        slab_arena.view(self.store_dir).sweep()
        out: List[dict] = []
        with self._lock:
            for path, (fsize, charged) in list(self._pool.items()):
                try:
                    fd = os.open(path, os.O_RDWR)
                except OSError:
                    continue  # drained/reused concurrently
                try:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        fcntl.flock(fd, fcntl.LOCK_UN)
                    except OSError:
                        out.append({
                            "file": os.path.basename(path),
                            "file_size": fsize,
                            "charged": charged,
                            "holder_pids": memview.flock_holders(path),
                        })
                finally:
                    os.close(fd)
        self._pool_pinned_cache = (time.monotonic(), out)
        return out

    def arena_introspect(self) -> dict:
        """Owner-side arena summary: per-segment occupancy with live vs
        dead entry counts and coalesced **dead byte ranges** (the input
        a ``fallocate(PUNCH_HOLE)`` reclamation pass would punch),
        recycling-pool and leased-vs-sealed stats, per-client slab
        charge, and the spill/overshoot tallies — the ``arena`` block of
        this node's memview snapshot."""
        now = time.monotonic()
        with self._lock:
            segs = []
            per_client: Dict[str, int] = {}
            for seg in sorted(self._segments.values(),
                              key=lambda s: s.seg_id):
                dead_bytes = sum(seg.dead.values())
                denom = seg.live_bytes + dead_bytes
                segs.append({
                    "seg_id": seg.seg_id,
                    "size": seg.size,
                    "leased_to": seg.leased_to,
                    "writer": seg.writer,
                    "live_entries": len(seg.live),
                    "dead_entries": len(seg.dead),
                    "live_bytes": seg.live_bytes,
                    "dead_bytes": dead_bytes,
                    "dead_ranges": memview.coalesce_ranges(
                        seg.dead.items()),
                    "fragmentation": dead_bytes / denom if denom else 0.0,
                    "idle_s": round(now - seg.last_access, 3),
                    "reserved": seg.reserved,
                    "punched_bytes": sum(seg.punched.values()),
                })
                charge_to = seg.leased_to or seg.writer or "_unknown"
                per_client[charge_to] = \
                    per_client.get(charge_to, 0) + seg.size
            pool = [{"file": os.path.basename(p), "file_size": f,
                     "charged": c} for p, (f, c) in self._pool.items()]
            out = {
                "capacity": self.capacity,
                "used": self._used,
                "live_bytes": self._slab_live_bytes,
                "dead_bytes": self._slab_dead_bytes,
                "punched_bytes": self._slab_punched_bytes,
                "punched_physical_bytes": self._slab_punched_physical,
                "fragmentation": self.arena_fragmentation(),
                "segments": segs,
                "leased_segments": sum(
                    1 for s in self._segments.values() if s.leased_to),
                "sealed_segments": sum(
                    1 for s in self._segments.values() if not s.leased_to),
                "pool": pool,
                "pool_bytes": sum(c for _f, c in self._pool.values()),
                "per_client_bytes": per_client,
                "file_objects": len(self._sizes),
                "file_bytes": sum(self._sizes.values()),
                "pinned_objects": len(self._pinned),
                "spilled": self._spilled_stats_locked(),
            }
        out["pool_pinned"] = self.pool_pinned()  # probes flocks: no lock
        return out

    def memview_objects(self, limit: int = 10_000) -> List[dict]:
        """Per-object lifecycle rows from this store's ledger: state
        (arena / external one-file / spilled), size, backing segment,
        pin count, owner (the segment's writing client), and age."""
        from itertools import islice

        now = time.monotonic()
        rows: List[dict] = []
        with self._lock:
            for oid, ent in islice(self._slab_objs.items(), limit):
                seg_id, off, total = ent[:3]
                ts = ent[3] if len(ent) > 3 else None
                seg = self._segments.get(seg_id)
                row = {
                    "object_id": oid.hex(),
                    "state": "arena",
                    "size": total,
                    "seg": seg_id,
                    "off": off,
                    "pins": self._pinned.get(oid, 0),
                    "owner": seg.writer if seg is not None else None,
                    "age_s": round(now - ts, 3) if ts is not None else None,
                }
                # ledger-persisted creation callsite (rode the owner's
                # slab report): survives the owner's death, so a leak
                # verdict still names the line that made the object
                if len(ent) > 4 and ent[4]:
                    row["callsite"] = ent[4]
                rows.append(row)
            room = max(0, limit - len(rows))
            for oid, size in islice(self._sizes.items(), room):
                ts = self._lru.get(oid)
                rows.append({
                    "object_id": oid.hex(),
                    "state": "external",
                    "size": size,
                    "pins": self._pinned.get(oid, 0),
                    # time-since-last-touch is a FLOOR on age: enough
                    # for the leak verdicts' in-flight-report gate (a
                    # just-registered object reads young)
                    "age_s": round(now - ts, 3) if ts is not None
                    else None,
                    "idle_s": round(now - ts, 3) if ts is not None
                    else None,
                })
            room = max(0, limit - len(rows))
            for oid, size in islice(self._spilled.items(), room):
                ts = self._spilled_at.get(oid)
                rows.append({
                    "object_id": oid.hex(),
                    "state": "spilled",
                    "size": size,
                    "pins": self._pinned.get(oid, 0),
                    "age_s": round(now - ts, 3) if ts is not None
                    else None,
                })
        return rows
