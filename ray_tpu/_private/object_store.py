"""Shared-memory local object store (plasma analog).

The reference's plasma store (ray: src/ray/object_manager/plasma/store.h) is a
shm arena with create/seal/get/release and LRU eviction; workers map segments
read-only for zero-copy reads. Here each sealed object is a file in a
``/dev/shm``-backed session directory mapped with ``mmap``:

  layout:  [8B magic][8B metadata_len][8B data_len][metadata][data]

Writers create ``<id>.building`` then atomically rename to ``<id>.obj`` on
seal, so any process on the node can open + mmap a sealed object without
talking to a broker: the data plane is the kernel page cache, exactly one
copy per node. Accounting (capacity, pinning, LRU eviction) is done by the
raylet process that owns the store directory; readers in other processes only
open/mmap.

A C++ implementation with the same on-disk format can replace the
writer/accounting path without changing readers.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Iterable, Optional

from ray_tpu._private.ids import ObjectID

_MAGIC = b"RTPUOBJ1"
_HEADER = 24


class ObjectStoreFullError(Exception):
    pass


@dataclass
class ObjectBuffer:
    """A sealed object mapped into this process (zero-copy views)."""

    object_id: ObjectID
    metadata: bytes
    data: memoryview
    _mmap: mmap.mmap = None
    _file: object = None

    def release(self):
        if self._mmap is not None:
            try:
                self.data.release()
            except BufferError:
                pass
            self._mmap.close()
            self._file.close()
            self._mmap = None


def _obj_path(store_dir: str, object_id: ObjectID) -> str:
    return os.path.join(store_dir, object_id.hex() + ".obj")


def read_object(store_dir: str, object_id: ObjectID) -> Optional[ObjectBuffer]:
    """Open and mmap a sealed object. Returns None if absent. Any process.

    Readers hold a SHARED flock on the file for the buffer's lifetime —
    the free path's page-recycling pool takes a non-blocking EXCLUSIVE
    flock before recycling, so pages a live zero-copy view still maps can
    never be rewritten; the pool falls back to unlink (inode stays intact
    for existing mappings). The post-lock inode recheck closes the
    open->lock race against a concurrent pool rename."""
    import fcntl

    path = _obj_path(store_dir, object_id)
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return None
    try:
        fcntl.flock(f.fileno(), fcntl.LOCK_SH)
        if os.fstat(f.fileno()).st_ino != os.stat(path).st_ino:
            f.close()  # pooled/recycled between open and lock: gone
            return None
    except OSError:
        f.close()
        return None
    m = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    if m[:8] != _MAGIC:
        m.close()
        f.close()
        raise IOError(f"corrupt object {object_id}")
    meta_len = int.from_bytes(m[8:16], "little")
    data_len = int.from_bytes(m[16:24], "little")
    metadata = bytes(m[_HEADER : _HEADER + meta_len])
    data = memoryview(m)[_HEADER + meta_len : _HEADER + meta_len + data_len]
    return ObjectBuffer(object_id, metadata, data, _mmap=m, _file=f)


def object_exists(store_dir: str, object_id: ObjectID) -> bool:
    return os.path.exists(_obj_path(store_dir, object_id))


def write_object(
    store_dir: str,
    object_id: ObjectID,
    metadata: bytes,
    buffers: Iterable,
    total_data_len: int,
) -> int:
    """Create + seal an object from buffers. Returns bytes written.

    Safe from any process; accounting is reconciled by the owning store's
    directory scan. Writing an already-sealed id is a no-op (objects are
    immutable, so double-writes are benign).
    """
    final = _obj_path(store_dir, object_id)
    if os.path.exists(final):
        return 0
    from ray_tpu._private import native_store

    if native_store.available():
        return native_store.write_object(
            store_dir, object_id.hex(), metadata, buffers, total_data_len
        )
    tmp = final + f".building.{os.getpid()}"
    size = _HEADER + len(metadata) + total_data_len
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(len(metadata).to_bytes(8, "little"))
        f.write(total_data_len.to_bytes(8, "little"))
        f.write(metadata)
        for buf in buffers:
            f.write(buf)
    os.rename(tmp, final)
    return size


def make_local_store(store_dir: str, capacity_bytes: int,
                     spill_dir: Optional[str] = None):
    """Owner-side store factory: native C++ store (src/librtpu_store.so)
    when loadable, else the pure-Python implementation. Both share the
    same on-disk format, so mixed clusters interoperate. ``spill_dir``
    (on real disk, not /dev/shm) enables spill-to-disk under memory
    pressure (ray: local_object_manager.h:40)."""
    from ray_tpu._private import native_store

    if native_store.available():
        return native_store.NativeLocalObjectStore(
            store_dir, capacity_bytes, spill_dir
        )
    return LocalObjectStore(store_dir, capacity_bytes, spill_dir)


class LocalObjectStore:
    """Owner-side store accounting: capacity, pinning, LRU eviction.

    Runs inside the raylet (one per node). Mirrors the reference's
    ObjectLifecycleManager + EvictionPolicy
    (ray: src/ray/object_manager/plasma/object_lifecycle_manager.h:101,
    eviction_policy.h:160).
    """

    def __init__(self, store_dir: str, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._sizes: Dict[ObjectID, int] = {}
        self._lru: "OrderedDict[ObjectID, float]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._used = 0
        self._spilled: Dict[ObjectID, int] = {}  # oid -> size on disk
        self.spilled_bytes_total = 0
        self.restored_bytes_total = 0

    # -- write path ----------------------------------------------------------
    def put(self, object_id: ObjectID, metadata: bytes, buffers, total_data_len: int):
        size = _HEADER + len(metadata) + total_data_len
        self._ensure_space(size)
        written = write_object(self.store_dir, object_id, metadata, buffers, total_data_len)
        if written:
            with self._lock:
                self._sizes[object_id] = written
                self._used += written
                self._lru[object_id] = time.monotonic()

    def register_external(self, object_id: ObjectID):
        """Account for an object written directly by a worker process."""
        path = _obj_path(self.store_dir, object_id)
        try:
            size = os.path.getsize(path)
        except FileNotFoundError:
            return
        with self._lock:
            if object_id not in self._sizes:
                self._sizes[object_id] = size
                self._used += size
                self._lru[object_id] = time.monotonic()

    # -- read path -----------------------------------------------------------
    def get(self, object_id: ObjectID) -> Optional[ObjectBuffer]:
        buf = read_object(self.store_dir, object_id)
        if buf is None and object_id in self._spilled:
            if self.restore_if_spilled(object_id):
                buf = read_object(self.store_dir, object_id)
        if buf is not None:
            with self._lock:
                if object_id in self._lru:
                    self._lru.move_to_end(object_id)
        return buf

    def contains(self, object_id: ObjectID) -> bool:
        return object_exists(self.store_dir, object_id) \
            or object_id in self._spilled

    # -- spilling (ray: local_object_manager.h SpillObjects/restore) ---------
    def _spill_path(self, object_id: ObjectID) -> str:
        return os.path.join(self.spill_dir, object_id.hex() + ".obj")

    def _spill_locked(self, object_id: ObjectID) -> bool:
        """Move one object's file from shm to the spill dir (cross-device
        copy + unlink); the object stays addressable and is restored on
        access. Pin counts survive: a spilled primary copy is still the
        primary copy."""
        src = _obj_path(self.store_dir, object_id)
        dst = self._spill_path(object_id)
        size = self._sizes.get(object_id, 0)
        try:
            with open(src, "rb") as fi, open(dst + ".tmp", "wb") as fo:
                while True:
                    chunk = fi.read(8 * 1024 * 1024)
                    if not chunk:
                        break
                    fo.write(chunk)
            os.replace(dst + ".tmp", dst)
            os.unlink(src)
        except OSError:
            try:
                os.unlink(dst + ".tmp")
            except OSError:
                pass
            return False
        self._sizes.pop(object_id, None)
        self._lru.pop(object_id, None)
        self._used -= size
        self._spilled[object_id] = size
        self.spilled_bytes_total += size
        return True

    def restore_if_spilled(self, object_id: ObjectID) -> bool:
        """Bring a spilled object back into shm (ray:
        spilled_object_reader.h — we restore whole objects)."""
        with self._lock:
            size = self._spilled.get(object_id)
            if size is None:
                return False
            self._ensure_space_locked(size)
            src = self._spill_path(object_id)
            dst = _obj_path(self.store_dir, object_id)
            try:
                with open(src, "rb") as fi, open(dst + ".tmp", "wb") as fo:
                    while True:
                        chunk = fi.read(8 * 1024 * 1024)
                        if not chunk:
                            break
                        fo.write(chunk)
                os.replace(dst + ".tmp", dst)
                os.unlink(src)
            except OSError:
                try:
                    os.unlink(dst + ".tmp")
                except OSError:
                    pass
                return False
            self._spilled.pop(object_id, None)
            self._sizes[object_id] = size
            self._used += size
            self._lru[object_id] = time.monotonic()
            self.restored_bytes_total += size
            return True

    # -- lifecycle -----------------------------------------------------------
    def pin(self, object_id: ObjectID):
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._delete_locked(object_id)

    def _delete_locked(self, object_id: ObjectID):
        try:
            os.unlink(_obj_path(self.store_dir, object_id))
        except FileNotFoundError:
            pass
        if self._spilled.pop(object_id, None) is not None:
            try:
                os.unlink(self._spill_path(object_id))
            except FileNotFoundError:
                pass
        size = self._sizes.pop(object_id, 0)
        self._used -= size
        self._lru.pop(object_id, None)
        self._pinned.pop(object_id, None)

    def _ensure_space(self, size: int):
        with self._lock:
            self._ensure_space_locked(size)

    def _ensure_space_locked(self, size: int):
        if self._used + size <= self.capacity:
            return
        # LRU-evict unpinned objects until there is room.
        for oid in list(self._lru.keys()):
            if self._used + size <= self.capacity:
                break
            if oid in self._pinned:
                continue
            self._delete_locked(oid)
        # Still short: spill LRU objects (pinned primaries included) to
        # disk instead of erroring (ray: local_object_manager.h:40).
        if self._used + size > self.capacity and self.spill_dir:
            for oid in list(self._lru.keys()):
                if self._used + size <= self.capacity:
                    break
                self._spill_locked(oid)
        if self._used + size > self.capacity:
            raise ObjectStoreFullError(
                f"object of size {size} does not fit: used={self._used} "
                f"capacity={self.capacity} (all remaining objects pinned)"
            )

    def used_bytes(self) -> int:
        return self._used

    def spilled_stats(self):
        with self._lock:
            return {
                "spilled_objects": len(self._spilled),
                "spilled_bytes_total": self.spilled_bytes_total,
                "restored_bytes_total": self.restored_bytes_total,
            }

    def object_ids(self):
        with self._lock:
            return list(self._sizes.keys()) + list(self._spilled.keys())
