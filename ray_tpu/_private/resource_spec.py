"""Node resource autodetection.

Analog of ray: python/ray/_private/resource_spec.py, with the TPU delta the
reference lacks (its accelerators are NVIDIA-only,
ray: python/ray/_private/resource_spec.py:175-182,
util/accelerators/accelerators.py:1-7): TPU chips are a first-class "TPU"
resource, and ICI topology is advertised as node labels so placement-group
STRICT_PACK can target one slice. Detection is env-driven
(TPU_CHIP_COUNT / TPU_TOPOLOGY / TPU_WORKER_ID, as set by GKE / QR runtimes);
probing via jax.devices() is opt-in (config flag tpu_autodetect) because
initializing libtpu claims the chips for the probing process.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

from ray_tpu._private.config import GLOBAL_CONFIG as cfg


def detect_resources() -> Tuple[Dict[str, float], Dict[str, str]]:
    resources: Dict[str, float] = {}
    labels: Dict[str, str] = {}
    resources["CPU"] = float(os.cpu_count() or 1)
    try:
        import psutil

        mem = psutil.virtual_memory().total
    except Exception:
        mem = 8 * 1024**3
    resources["memory"] = float(int(mem * 0.7))
    resources["object_store_memory"] = float(cfg.object_store_memory)

    chips = os.environ.get("TPU_CHIP_COUNT")
    if chips is None and cfg.tpu_autodetect:
        try:
            import jax

            devs = [d for d in jax.devices() if d.platform != "cpu"]
            chips = str(len(devs)) if devs else None
            if devs:
                labels["tpu-device-kind"] = getattr(devs[0], "device_kind", "tpu")
        except Exception:
            chips = None
    if chips:
        n = float(chips)
        if n > 0:
            resources["TPU"] = n
            accel = os.environ.get("TPU_ACCELERATOR_TYPE")
            if accel:
                labels["tpu-accelerator-type"] = accel
                resources[f"TPU-{accel}"] = n
    topo = os.environ.get("TPU_TOPOLOGY")
    if topo:
        labels["tpu-topology"] = topo
    slice_name = os.environ.get("TPU_SLICE_NAME") or os.environ.get("TPU_NAME")
    if slice_name:
        labels["tpu-slice"] = slice_name
    worker_id = os.environ.get("TPU_WORKER_ID")
    if worker_id is not None:
        labels["tpu-worker-id"] = worker_id
    return resources, labels
