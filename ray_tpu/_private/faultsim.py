"""Deterministic, seeded network fault injection for the RPC substrate.

Analog of the reference's chaos knobs (ray: RAY_testing_rpc_failure injects
per-method request/response failures, testing_asio_delay_us injects delays —
src/ray/common/ray_config_def.h). rpcio consults this module at three
well-defined points — frame enqueue (``Connection`` send path), the flush
loop, and ``connect()`` — and each armed rule decides from its OWN seeded
PRNG, so a chaos failure is replayable by re-running with the logged spec.

Spec syntax (``RAY_TPU_RPC_FAULTS``), rules separated by ``;`` or newlines::

    pattern:kind:prob:seed[:param]

``kind`` is one of:

  drop       sever the connection mid-frame (partial bytes hit the wire,
             then a hard close — the peer sees a truncated frame)
  delay      stall the connection's write stream ``param`` ms (default 50)
             before this frame (in-order: TCP never reorders, neither do we)
  dup        enqueue the frame twice (exercises receiver-side dedup)
  corrupt    flip one byte in the frame head (length-covered, CRC-covered
             region — the receiver must detect it and reset)
  partition  black-hole traffic to a peer: new dials fail, frames on
             existing connections are silently discarded (keepalive then
             declares the peer dead). ``prob`` is ignored — a matching
             partition rule is always on (a real partition is not a coin
             flip per packet).
  kill       SIGKILL the process sending a matching frame — rank death
             as a seeded-replayable chaos event (nothing flushes, no
             handlers run: exactly what a spot reclaim or OOM kill looks
             like to the rest of the gang). Match a method only the
             target process sends (or replies to) to scope the blast.

``pattern`` is a regex matched against the RPC *method name* for frame
kinds, and against ``"<self_id>><peer>"`` for ``partition`` (so a rule
can partition one process from one peer without touching the rest:
``nodeA.*>.*:6801:partition:1:0``). ``<peer>`` is the dialed
``host:port`` for client connections, and — once the peer has registered
an identity on the connection (``meta["node_id"]``, stamped on both
sides of raylet peer links) — ``"<node_id>|<addr>"``, so rules can name
a peer by node id and black-hole BOTH directions of a duplex socket
(a server-accepted conn's socket addr is just an ephemeral port no rule
could name). Processes label themselves via ``set_self_id`` (raylets use
their node id, the GCS ``gcs:<port>``, workers/drivers
``worker:<client_id>``); the default is ``pid:<pid>``.

Dynamic control: ``RAY_TPU_RPC_FAULTS_FILE`` names a file holding the same
spec syntax, re-read when its mtime/size changes (checked at most every
0.2 s) — the lever tests use to create and then HEAL a partition across
live subprocesses. Both sources combine; the env spec parses once.

Near-zero cost when idle: the env is probed once; after that
``active_plan()`` is two module-attribute reads returning None until a
spec is configured (arm at process start via env, or at runtime via
``install()``).
"""

from __future__ import annotations

import logging
import os
import random
import re
import threading
import time
from typing import List, Optional, Tuple

logger = logging.getLogger(__name__)

KINDS = ("drop", "delay", "dup", "corrupt", "partition", "kill")

_FILE_POLL_S = 0.2


class FaultRule:
    __slots__ = ("pattern", "kind", "prob", "seed", "param", "rx", "rng")

    def __init__(self, pattern: str, kind: str, prob: float, seed: int,
                 param: Optional[float] = None):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self.pattern = pattern
        self.kind = kind
        self.prob = prob
        self.seed = seed
        self.param = param
        self.rx = re.compile(pattern)
        self.rng = random.Random(seed)

    def fires(self, text: str) -> bool:
        if self.rx.search(text) is None:
            return False
        if self.kind == "partition":
            return True  # stateful, not probabilistic
        # the PRNG advances only on matches, so the decision sequence for a
        # given (spec, method stream) is reproducible from the seed
        return self.rng.random() < self.prob

    def __repr__(self):
        return (f"FaultRule({self.pattern!r}:{self.kind}:{self.prob}"
                f":{self.seed}" + (f":{self.param}" if self.param else "") + ")")


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse a fault spec; malformed rules are logged and skipped (a typo
    in a chaos env var must not crash a raylet at boot)."""
    rules: List[FaultRule] = []
    for raw in re.split(r"[;\n]+", spec or ""):
        raw = raw.strip()
        if not raw or raw.startswith("#"):
            continue
        # rsplit: the pattern itself may contain ':' (host:port regexes)
        parts = raw.rsplit(":", 4)
        for take in (5, 4):  # with and without the optional param field
            if len(parts) < take:
                continue
            head = raw.rsplit(":", take - 1)
            if len(head) != take or head[1] not in KINDS:
                continue
            try:
                param = float(head[4]) if take == 5 else None
                rules.append(FaultRule(head[0], head[1], float(head[2]),
                                       int(head[3]), param))
            except (ValueError, re.error) as e:
                logger.warning("faultsim: skipping malformed rule %r: %s",
                               raw, e)
            break
        else:
            logger.warning("faultsim: skipping malformed rule %r", raw)
    return rules


class FaultPlan:
    """The armed rule set for this process."""

    def __init__(self, rules: List[FaultRule], source: str = ""):
        self.method_rules = [r for r in rules if r.kind != "partition"]
        self.partition_rules = [r for r in rules if r.kind == "partition"]
        self.source = source

    def on_send(self, method: str,
                peer: Optional[str]) -> Optional[Tuple[str, FaultRule]]:
        """Decide the fate of one outbound frame. Returns (kind, rule) for
        the first rule that fires, or None. Internal keepalive frames are
        exempt from method faults (they ARE the failure detector) but not
        from partition (a black hole swallows pings too)."""
        if peer is not None and self.partitioned(peer):
            return ("partition", self.partition_rules[0])
        if method.startswith("__"):
            return None
        for rule in self.method_rules:
            if rule.fires(method):
                return (rule.kind, rule)
        return None

    def partitioned(self, peer: str) -> bool:
        key = f"{_SELF_ID}>{peer}"
        return any(r.fires(key) for r in self.partition_rules)

    def on_connect(self, addr: str) -> bool:
        """True when new dials to ``addr`` must be refused (partition)."""
        return bool(self.partition_rules) and self.partitioned(addr)


# --- module state -------------------------------------------------------
_SELF_ID = f"pid:{os.getpid()}"
_PLAN: Optional[FaultPlan] = None
# set once a probe finds neither env var configured: the per-frame hot
# path then short-circuits to one module-attribute read (env vars are
# snapshotted at first use — arm at process start or via install())
_DISARMED = False
_LOCK = threading.Lock()
_file_state = {"path": None, "sig": None, "next_check": 0.0, "rules": []}
_env_state = {"spec": None, "rules": []}
_installed: Optional[FaultPlan] = None


def set_self_id(self_id: str):
    """Label this process for partition-rule matching (raylet: node id,
    GCS: gcs:<port>, worker/driver: worker:<client_id>)."""
    global _SELF_ID
    _SELF_ID = self_id


def self_id() -> str:
    return _SELF_ID


def install(spec: str) -> FaultPlan:
    """Arm a plan programmatically (tests). Overrides env/file sources
    until ``clear()``."""
    global _installed, _PLAN
    _installed = FaultPlan(parse_spec(spec), source="install")
    _rebuild()
    logger.warning("faultsim armed (install): %s", spec)
    return _installed


def clear():
    global _installed, _PLAN, _DISARMED
    _installed = None
    _env_state["spec"] = None
    _env_state["rules"] = []
    _file_state["path"] = None
    _file_state["sig"] = None
    _file_state["rules"] = []
    _PLAN = None
    _DISARMED = False  # re-probe the env on next use (tests re-arm)


def _rebuild():
    global _PLAN
    rules = list(_env_state["rules"]) + list(_file_state["rules"])
    if _installed is not None:
        _PLAN = _installed
    elif rules:
        _PLAN = FaultPlan(rules, source="env/file")
    else:
        _PLAN = None


def _load_env():
    spec = os.environ.get("RAY_TPU_RPC_FAULTS") or ""
    if spec != _env_state["spec"]:
        _env_state["spec"] = spec
        _env_state["rules"] = parse_spec(spec)
        if _env_state["rules"]:
            logger.warning(
                "faultsim armed from RAY_TPU_RPC_FAULTS=%r (replay a chaos "
                "failure by re-running with this exact spec)", spec)
        _rebuild()


def _load_file(path: str, now: float):
    _file_state["next_check"] = now + _FILE_POLL_S
    try:
        st = os.stat(path)
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        sig = None
    if sig == _file_state["sig"] and _file_state["path"] == path:
        return
    _file_state["path"] = path
    _file_state["sig"] = sig
    if sig is None:
        _file_state["rules"] = []
    else:
        try:
            with open(path) as f:
                spec = f.read()
        except OSError:
            spec = ""
        _file_state["rules"] = parse_spec(spec)
        logger.warning("faultsim reloaded %s: %d rule(s) [self_id=%s]",
                       path, len(_file_state["rules"]), _SELF_ID)
    _rebuild()


_injection_counters: dict = {}


def record_injection(kind: str, method: str):
    """Account one injected fault: a metric
    (``rpc_faults_injected_total{kind=...}``) plus — when tracing is on —
    a point span in the task-event log, so a chaos-lane failure
    correlates with the exact faults injected around it in the SAME
    cluster snapshot (metrics + timeline). Called by rpcio at the
    injection sites; must never raise into the send path."""
    try:
        c = _injection_counters.get(kind)
        if c is None:
            from ray_tpu._private import metrics_core

            c = _injection_counters[kind] = metrics_core.registry().counter(
                "rpc_faults_injected_total",
                "Faults injected by faultsim, by kind",
            ).labels(kind=kind)
        c.inc()
    except Exception:
        pass
    try:
        from ray_tpu.util import tracing

        if tracing.is_enabled():
            now = time.time()
            tracing.record_remote_span(
                f"faultsim::{kind}", now, now,
                {"trace_id": f"faultsim-{os.getpid()}", "span_id": "fault"},
                attributes={"kind": kind, "method": method,
                            "self_id": _SELF_ID},
            )
    except Exception:
        pass


def active_plan() -> Optional[FaultPlan]:
    """The armed plan, or None (the common case, two attribute reads)."""
    global _DISARMED
    if _installed is not None:
        return _installed
    if _DISARMED:
        return None
    path = os.environ.get("RAY_TPU_RPC_FAULTS_FILE")
    spec = os.environ.get("RAY_TPU_RPC_FAULTS")
    if not path and not spec:
        if _PLAN is not None:
            clear()
        _DISARMED = True
        return None
    with _LOCK:
        _load_env()
        if path:
            now = time.monotonic()
            if now >= _file_state["next_check"]:
                _load_file(path, now)
        elif _file_state["rules"]:
            _file_state["rules"] = []
            _rebuild()
        return _PLAN
