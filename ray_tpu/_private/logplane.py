"""Log plane core: per-task byte-range attribution + streaming helpers.

Analog of the reference's log pipeline (ray: python/ray/_private/
log_monitor.py tails per-worker files and publishes lines; worker.py
print_logs renders them on the driver with ``(pid=..., ip=...)`` prefixes
and a dedup window). TPU-native the pieces are split by process:

- workers (executor.py) record the byte offset of their own log file
  around user-code execution (``stdio_offset`` / ``attach_result_span``)
  and stamp the exact ``(log_file, start, end)`` span into the task-event
  pipeline — any finished task/actor method maps to an exact byte range
  of its worker's log, no grep required;
- raylets (raylet.py) tail their workers' files and attribute each line
  to a task by matching its byte offset against a per-worker
  ``SpanTable`` fed from the task events flowing through them;
- drivers (api.py) print the streamed lines with task-name prefixes and
  collapse identical lines fanning in from many workers via
  ``LogDeduplicator``.

Everything here is dependency-free and pure enough to unit test without
a cluster (see tests/test_logs.py).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional, Tuple

_TRUNC_MARK = b"... [truncated]"


# ---------------------------------------------------------------------------
# worker-side: log file identity + offset capture
# ---------------------------------------------------------------------------

def worker_log_path() -> Optional[str]:
    """This worker process's own log file (the raylet redirects worker
    stdout/stderr there and exports the path at spawn)."""
    return os.environ.get("RAY_TPU_WORKER_LOG_FILE") or None


def stdio_offset(flush: bool = True) -> Optional[int]:
    """Current end offset of this worker's log file. Flushes stdio first
    so buffered ``print()`` output is actually in the file — python
    block-buffers stdout when redirected, so without the flush a task's
    prints could land outside its recorded span (and reach the tailer a
    task late)."""
    path = worker_log_path()
    if not path:
        return None
    try:
        if flush:
            sys.stdout.flush()
            sys.stderr.flush()
        return os.path.getsize(path)
    except (OSError, ValueError):
        # ValueError: stdio already closed during interpreter teardown
        return None


def attach_result_span(result: dict, start: Optional[int]) -> dict:
    """Stamp the executed task's exact log byte range onto its result
    dict (picked up by the raylet / direct-push event emitters)."""
    if start is None:
        return result
    end = stdio_offset()
    path = worker_log_path()
    if end is None or path is None:
        return result
    result["log_span"] = {
        "file": os.path.basename(path), "start": start, "end": max(end, start),
    }
    return result


def open_event_fields() -> dict:
    """Task-event fields announcing where in the log a task is ABOUT to
    start writing (a provisional open span; the exact range arrives with
    the FINISHED/FAILED event)."""
    start = stdio_offset()
    path = worker_log_path()
    if start is None or path is None:
        return {}
    return {"log_file": os.path.basename(path), "log_start": start}


# ---------------------------------------------------------------------------
# raylet-side: byte-offset -> task-name attribution
# ---------------------------------------------------------------------------

class SpanTable:
    """Byte-range -> task attribution for ONE worker's log file.

    Fed from the task events flowing through the raylet: RUNNING events
    open a provisional span at their ``log_start``; FINISHED/FAILED
    events close it with the executor-measured exact range. ``resolve``
    prefers closed (exact) spans over open ones, so lines printed by a
    previous task before its buffers flushed never mis-attribute to the
    next task whose provisional start preceded them.
    """

    def __init__(self, history: int = 128):
        self.history = history
        self._open: Dict[str, Tuple[int, str]] = {}  # task_id -> (start, name)
        self._closed: List[Tuple[int, int, str]] = []  # (start, end, name)

    def open_span(self, task_id: str, name: str, start: int):
        self._open[task_id] = (int(start), name)
        if len(self._open) > self.history:  # leaked opens (lost close)
            self._open.pop(next(iter(self._open)))

    def close_span(self, task_id: str, name: str, start: int, end: int):
        self._open.pop(task_id, None)
        if end > start:
            self._closed.append((int(start), int(end), name))
            if len(self._closed) > self.history:
                del self._closed[: len(self._closed) - self.history]

    def discard(self, task_id: str):
        self._open.pop(task_id, None)

    def resolve(self, offset: int) -> Optional[str]:
        """Task name owning the byte at ``offset`` (newest match wins)."""
        for start, end, name in reversed(self._closed):
            if start <= offset < end:
                return name
        best = None
        best_start = -1
        for start, name in self._open.values():
            if best_start < start <= offset:
                best, best_start = name, start
        return best

    def prune(self, upto: int):
        """Drop closed spans entirely behind the tailer (their bytes have
        been published; nothing will ask again)."""
        self._closed = [s for s in self._closed if s[1] > upto]


def truncate_line(raw: bytes, limit: int) -> Tuple[bytes, bool]:
    """Cap one log line at ``limit`` bytes (length-capped records: a task
    dumping a multi-MB blob on one line must not balloon pubsub frames)."""
    if limit > 0 and len(raw) > limit:
        return raw[:limit] + _TRUNC_MARK, True
    return raw, False


# ---------------------------------------------------------------------------
# driver-side: identical-line dedup window
# ---------------------------------------------------------------------------

class LogDeduplicator:
    """Collapse identical lines fanning in from many workers.

    The first occurrence prints immediately; identical lines arriving
    within ``window_s`` are counted instead of printed, and when the
    window expires one summary line with a ``[repeated Nx]`` suffix is
    emitted (ray parity: worker.py's log deduplicator). Keyed on the raw
    line text — the whole point is collapsing the same line from N
    different workers/pids.
    """

    def __init__(self, window_s: float = 1.0, max_entries: int = 1024,
                 color: bool = True):
        self.window_s = window_s
        self.max_entries = max_entries
        self.color = color
        # line -> {"first": ts, "count": suppressed, "prefix": str}
        self._seen: Dict[str, dict] = {}

    def _summary(self, prefix: str, line: str, count: int) -> str:
        suffix = f"[repeated {count}x]"
        if self.color:
            suffix = f"\x1b[2m{suffix}\x1b[0m"
        return f"{prefix}{line} {suffix}"

    def feed(self, prefix: str, line: str,
             now: Optional[float] = None) -> List[str]:
        """Returns the lines to print for this arrival (possibly none —
        suppressed duplicate — possibly several: expired summaries drain
        ahead of the new line so output stays ordered)."""
        now = time.monotonic() if now is None else now
        out = self.flush(now=now)
        entry = self._seen.get(line)
        if entry is not None:
            entry["count"] += 1
            entry["prefix"] = prefix
            return out
        if len(self._seen) >= self.max_entries:
            stale = next(iter(self._seen))
            e = self._seen.pop(stale)
            if e["count"]:
                out.append(self._summary(e["prefix"], stale, e["count"]))
        self._seen[line] = {"first": now, "count": 0, "prefix": prefix}
        out.append(prefix + line)
        return out

    def flush(self, now: Optional[float] = None,
              force: bool = False) -> List[str]:
        """Emit ``[repeated Nx]`` summaries for expired windows (all
        windows when ``force``, e.g. at shutdown). Entries sit in
        insertion order and ``first`` is never updated, so the scan stops
        at the first live window — feed() calls this per line, and a
        full scan there was O(lines x window-population), the measured
        hot spot of the BENCH_LOG_OVERHEAD lane."""
        now = time.monotonic() if now is None else now
        expired = []
        for line, entry in self._seen.items():  # NO dict copy: feed()
            # calls this per line, and copying the window population per
            # line was the measured hot spot of BENCH_LOG_OVERHEAD
            if not force and now - entry["first"] <= self.window_s:
                break  # everything after was inserted later: still live
            expired.append((line, entry))
        out = []
        for line, entry in expired:
            del self._seen[line]
            if entry["count"]:
                out.append(
                    self._summary(entry["prefix"], line, entry["count"]))
        return out
