"""ctypes binding for the native (C++) scheduling-policy engine.

The reference's node-selection policies are C++ (ray:
src/ray/raylet/scheduling/policy/*, cluster_resource_scheduler.h); here
they live in src/scheduler.cpp behind a C ABI. `pick_node` and
`place_bundles` in ray_tpu/_private/common.py dispatch to this module when
the shared library is available (set ``RAY_TPU_NATIVE_SCHED=0`` to force
the pure-Python policies); tests/test_native_sched.py differential-tests
both implementations on randomized clusters — they must agree node-for-node.

Wire format: the cluster view is serialized per call (clusters are
hundreds of nodes, not millions; serialization is nanoseconds against an
RPC-scale scheduling decision) as one node per line:
``node_id|alive|total|avail|labels`` with comma-joined ``k=v`` lists.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

from ray_tpu._private import native_store

_OUT_CAP = 1 << 20

_configured = False


def _lib():
    global _configured
    lib = native_store.load_library()
    if lib is None:
        return None
    if not hasattr(lib, "rtpu_sched_pick"):
        return None  # stale .so from before the scheduler landed
    if not _configured:
        lib.rtpu_sched_pick.restype = ctypes.c_int
        lib.rtpu_sched_pick.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_char_p, ctypes.c_ulong,
        ]
        lib.rtpu_sched_place_bundles.restype = ctypes.c_int
        lib.rtpu_sched_place_bundles.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_ulong,
        ]
        _configured = True
    return lib


def available() -> bool:
    import os

    if os.environ.get("RAY_TPU_NATIVE_SCHED", "1") == "0":
        return False
    return _lib() is not None


_RESERVED = set(",|:;=\n")


def _clean(s) -> bool:
    return not (set(str(s)) & _RESERVED)


def _on_grid(res: Dict[str, float]) -> bool:
    """The C++ engine quantizes to 1e-4 fixed point (llround); a value off
    that grid could make native and the Python-oracle policies pick
    different nodes. Screen such inputs out so they take the oracle path."""
    for v in res.values():
        scaled = float(v) * 1e4
        if abs(scaled - round(scaled)) > 1e-6:
            return False
    return True


def encodable(nodes, demand, strategy=None,
              bundles=None) -> bool:
    """The line-oriented wire format has no escaping: any node id, resource
    name, label, or selector value containing a separator char (or an
    empty-string selector value, which the format cannot represent) must be
    scheduled by the Python oracle instead; likewise values off the engine's
    1e-4 fixed-point grid (see _on_grid)."""
    for n in nodes:
        if not _clean(n.node_id):
            return False
        for res in (n.resources_total, n.resources_available):
            if not all(_clean(k) for k in res):
                return False
            if not _on_grid(res):
                return False
        for k, v in (n.labels or {}).items():
            if not (_clean(k) and _clean(v)):
                return False
    if not all(_clean(k) for k in demand or {}):
        return False
    if demand and not _on_grid(demand):
        return False
    for b in bundles or []:
        if not all(_clean(k) for k in b):
            return False
        if not _on_grid(b):
            return False
    if strategy is not None:
        for sel in (getattr(strategy, "labels_hard", None),
                    getattr(strategy, "labels_soft", None)):
            for k, cond in (sel or {}).items():
                if not _clean(k):
                    return False
                vals = cond if isinstance(cond, (list, tuple, set)) else (
                    [] if cond is None else [cond]
                )
                for v in vals:
                    if str(v) == "" or not _clean(v) or (
                        isinstance(v, str) and v == "!"
                    ):
                        return False
    return True


def _res_str(res: Dict[str, float]) -> str:
    return ",".join(f"{k}={v:.10g}" for k, v in res.items())


def _nodes_blob(nodes) -> bytes:
    lines = []
    for n in nodes:
        labels = ",".join(f"{k}={v}" for k, v in (n.labels or {}).items())
        lines.append(
            f"{n.node_id}|{1 if n.alive else 0}|"
            f"{_res_str(n.resources_total)}|"
            f"{_res_str(n.resources_available)}|{labels}"
        )
    return "\n".join(lines).encode()


def _selector_str(sel: Optional[dict]) -> bytes:
    """Encode a label selector {key: cond} where cond is a str (equals),
    a list (in), None (exists), or "!value" (not equals)."""
    if not sel:
        return b""
    parts = []
    for k, cond in sel.items():
        if cond is None:
            parts.append(f"{k}:ex:")
        elif isinstance(cond, (list, tuple, set)):
            vals = list(dict.fromkeys(str(v) for v in cond))
            parts.append(f"{k}:in:{';'.join(vals)}")
        elif isinstance(cond, str) and cond.startswith("!"):
            parts.append(f"{k}:nin:{cond[1:]}")
        else:
            parts.append(f"{k}:in:{cond}")
    return ",".join(parts).encode()


def pick_node(nodes, demand: Dict[str, float], strategy, local_node_id,
              rr_state: List[int], spread_threshold: float) -> Optional[str]:
    lib = _lib()
    out = ctypes.create_string_buffer(_OUT_CAP)
    rr = ctypes.c_longlong(rr_state[0])
    rc = lib.rtpu_sched_pick(
        _nodes_blob(nodes), _res_str(demand).encode(),
        strategy.kind.encode(),
        (strategy.node_id or "").encode(), 1 if strategy.soft else 0,
        _selector_str(getattr(strategy, "labels_hard", None)),
        _selector_str(getattr(strategy, "labels_soft", None)),
        (local_node_id or "").encode(), spread_threshold,
        ctypes.byref(rr), out, _OUT_CAP,
    )
    rr_state[0] = rr.value
    if rc != 1:
        return None
    return out.value.decode()


def place_bundles(nodes, bundles: List[Dict[str, float]],
                  strategy: str) -> Optional[List[str]]:
    if not bundles:
        return []  # the empty wire blob would decode as [''], not []
    lib = _lib()
    out = ctypes.create_string_buffer(_OUT_CAP)
    blob = "\n".join(_res_str(b) for b in bundles).encode()
    rc = lib.rtpu_sched_place_bundles(
        _nodes_blob(nodes), blob, strategy.encode(), out, _OUT_CAP,
    )
    if rc != 1:
        return None
    return out.value.decode().split("\n")
