"""Lazy JAX platform pinning.

The JAX_PLATFORMS env var alone does not stop plugin backends (e.g. the
axon TPU tunnel registered by a sitecustomize at interpreter start) from
initializing — a dead tunnel then hangs the first dispatch indefinitely.
jax.config.update IS honored, so pin the platform through the config API
the moment jax finishes importing (or immediately if it already has).
Used by worker_main (worker processes) and ray_tpu/__init__ (drivers).
"""

from __future__ import annotations


def _pin_jax_platform_on_import(platforms: str):
    """Arrange for jax.config.update("jax_platforms", ...) to run right
    after jax finishes importing — wherever that import happens. If jax is
    already in (e.g. a sitecustomize imported it at interpreter start),
    pin immediately."""
    import sys

    if "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", platforms)
        except Exception:
            pass
        return

    import importlib.abc
    import importlib.util

    class _Finder(importlib.abc.MetaPathFinder):
        def __init__(self):
            self._busy = False

        def find_spec(self, name, path=None, target=None):
            if name != "jax" or self._busy:
                return None
            self._busy = True  # find_spec below re-enters the meta path
            try:
                spec = importlib.util.find_spec("jax")
            finally:
                self._busy = False
            if spec is None or spec.loader is None:
                return None
            orig_loader = spec.loader
            finder = self

            class _Loader(importlib.abc.Loader):
                def create_module(self, spec):
                    return orig_loader.create_module(spec)

                def exec_module(self, module):
                    orig_loader.exec_module(module)
                    # one-shot: jax is pinned; stop intercepting imports
                    try:
                        sys.meta_path.remove(finder)
                    except ValueError:
                        pass
                    try:
                        module.config.update("jax_platforms", platforms)
                    except Exception:
                        pass

            spec.loader = _Loader()
            return spec

    sys.meta_path.insert(0, _Finder())
