"""GCS server process entrypoint (analog of ray: src/ray/gcs/gcs_server/
gcs_server_main.cc)."""

from __future__ import annotations

import argparse
import asyncio
import logging
import os


async def amain(args):
    from ray_tpu._private.rpcio import enable_eager_tasks

    enable_eager_tasks(asyncio.get_running_loop())
    from ray_tpu._private.gcs import GcsServer

    server = GcsServer(host=args.host, port=args.port,
                       persist_path=args.persist_path,
                       cluster_id=args.cluster_id)
    port = await server.start()
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.rename(tmp, args.port_file)
    await asyncio.Event().wait()


def main():
    from ray_tpu._private.profiling import maybe_profile

    maybe_profile("gcs")
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--port-file", default=None)
    parser.add_argument("--cluster-id", default=None)
    parser.add_argument("--persist-path", default=None,
                        help="append-log file enabling GCS fault tolerance")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[gcs] %(levelname)s %(name)s: %(message)s",
    )
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
